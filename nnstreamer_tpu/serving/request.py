"""Serving requests + typed admission errors (L6 serving).

A :class:`Request` is one client submission travelling through the
continuous-batching scheduler (``serving/scheduler.py``): admission →
priority queue → batch formation → device execution → completion. Every
request carries its own observability record (``metrics``) — enqueue
time, batch id, shape bucket, queue wait, device time, ttft and total
latency — the per-request half of ``serving.metrics_snapshot()``.

Hermes (arxiv 2409.04249) frames scheduling/batch-formation policy, not
kernel speed, as the utilization lever for streaming inference; the
typed-shedding contract here is the admission-control half of that: a
request the system cannot serve within budget fails FAST with a typed
error instead of rotting in an unbounded buffer.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Optional, Sequence, Tuple


class ServingError(RuntimeError):
    """Base class for serving-subsystem errors."""


class AdmissionError(ServingError):
    """The request was rejected/shed and NEVER executed — admission
    control (queue depth / deadline budget) refused it. Typed so callers
    can distinguish shedding from execution failure and retry elsewhere
    or degrade gracefully."""


class QueueFullError(AdmissionError):
    """Queue depth is at ``max_depth`` — the server is saturated."""


class DeadlineExceededError(AdmissionError):
    """The deadline is unmeetable: already expired at admission, expired
    while queued, or the estimated queue wait exceeds the remaining
    budget (predictive shed — reject NOW rather than execute a result
    nobody will read)."""


class MemoryPressureError(AdmissionError):
    """Admitting this request's tensors would push projected serving
    memory past the configured watermark (``obs.memory.AdmissionGuard``)
    — shed NOW, typed, instead of OOM-ing a formed batch mid-execution
    and failing every coalesced neighbor with it."""


class OverloadShedError(AdmissionError):
    """The serving plane is at capacity and cannot grow (replica ceiling
    reached or memory headroom forbids another replica —
    ``service/autoscaler.py``), so requests at or below the armed
    priority cutoff are refused immediately with THIS typed error
    instead of queueing into a p99 collapse for everyone. Higher-priority
    traffic keeps flowing; callers see a deliberate shed they can back
    off from, never a timeout."""


class SchedulerClosedError(ServingError):
    """Submission after ``close()``."""


_req_counter = itertools.count()


class Request:
    """One unit of work: ``tensors`` (leading axis = rows to batch over),
    a priority (LOWER sorts first), an optional absolute deadline
    (``time.monotonic`` seconds), and a completion future.

    For decode-mode scheduling (``DecodeScheduler``) ``tensors[0]`` is a
    1-D int32 prompt and ``steps`` bounds generation length.
    """

    __slots__ = (
        "id", "tensors", "priority", "deadline", "steps", "eos_id",
        "metrics", "on_done", "_event", "_result", "_error", "tokens",
        "trace", "_span",
    )

    def __init__(self, tensors: Sequence, priority: int = 0,
                 deadline: Optional[float] = None, steps: int = 0,
                 eos_id: Optional[int] = None,
                 on_done: Optional[Callable[["Request"], None]] = None,
                 trace=None):
        self.id = next(_req_counter)
        self.tensors = tuple(tensors)
        self.priority = priority
        self.deadline = deadline
        self.steps = steps
        self.eos_id = eos_id
        self.on_done = on_done
        self.metrics: dict = {"enqueue_time": time.monotonic()}
        self._event = threading.Event()
        self._result: Optional[Tuple] = None
        self._error: Optional[BaseException] = None
        self.tokens: list = []  # decode mode: tokens emitted so far
        # request-scoped tracing (obs/context.py): the TraceContext this
        # request belongs to — propagated from the caller (query wire,
        # tensor_serving element) or minted at admission; batch spans
        # LINK to it (a coalesced batch serves N requests, so strict
        # parentage would be a lie)
        self.trace = trace
        self._span = None  # live admission span, ended by _finish

    # -- rows ---------------------------------------------------------------
    @property
    def rows(self) -> int:
        """Rows this request contributes to a batch (leading dim; a
        dimensionless scalar counts as one row)."""
        t = self.tensors[0]
        shape = getattr(t, "shape", ())
        return int(shape[0]) if shape else 1

    def bucket_key(self) -> tuple:
        """Requests coalesce only when their per-row signature matches —
        same trailing shape and dtype for every tensor (padding rows to a
        bucket then never shows jit a fresh signature)."""
        return tuple(
            (tuple(getattr(t, "shape", ())[1:]), str(getattr(t, "dtype", "")))
            for t in self.tensors)

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                > self.deadline)

    # -- completion ---------------------------------------------------------
    def _finish(self) -> None:
        self.metrics.setdefault(
            "total_latency_s",
            time.monotonic() - self.metrics["enqueue_time"])
        if self._span is not None:
            self._span.end(
                "ok" if self._error is None
                else f"error:{type(self._error).__name__}")
            self._span = None
        self._event.set()
        if self.on_done is not None:
            try:
                self.on_done(self)
            except Exception:  # noqa: BLE001 - a callback must not kill the loop
                from ..utils.log import logger

                logger.exception("serving: on_done callback failed for "
                                 "request %d", self.id)

    def complete(self, result: Tuple) -> None:
        self._result = result
        self._finish()

    def fail(self, error: BaseException) -> None:
        self._error = error
        self._finish()

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def result(self, timeout: Optional[float] = None) -> Tuple:
        """Block until the scheduler completes/sheds this request; returns
        the output tensors or raises the typed error that ended it."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"serving request {self.id} not completed in {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result
