"""nnstreamer_tpu.serving — continuous-batching request scheduler (L6).

The layer between ingress (``tensor_serving`` element, ``QueryServer``
TCP clients, or direct ``Scheduler.submit``) and model execution: merges
concurrent requests from many clients into full device batches so the
MXU runs at the batch size the TRAFFIC supports, not whatever one client
happens to send. See docs/serving.md.

Public surface:

* :class:`Scheduler` / :class:`DecodeScheduler` — the two loops;
* :class:`RequestQueue`, :class:`BatchFormer`, :class:`Request` — the
  building blocks, composable separately;
* :class:`ContinuousLMEngine` / :class:`PagedLMEngine` — slot-based LM
  decode state (dense per-slot caches vs block-table paged KV pool with
  COW prefix sharing, chunked prefill, and preempt/restore);
* :class:`KVPagePool` — the refcounted page allocator + prefix registry;
* :class:`SpeculativeLMEngine` (+ :class:`NgramDraft`/:class:`ModelDraft`)
  — draft-verify decoding riding the same join/retire loop;
* typed admission errors (:class:`AdmissionError` and friends);
* :func:`metrics_snapshot` — per-request/per-batch observability across
  every live scheduler;
* :func:`get_shared_scheduler` / :func:`release_shared_scheduler` — the
  refcounted per-key table ``tensor_serving`` elements share one device
  batch through (the query-server shared-handle idiom,
  query/server.py:169-221, applied to schedulers).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Tuple

from .batcher import Batch, BatchFormer  # noqa: F401
from .kv_pool import KVPagePool, PagePoolExhausted  # noqa: F401
from .lm_engine import ContinuousLMEngine, PagedLMEngine  # noqa: F401
from .metrics import ServingMetrics, metrics_snapshot  # noqa: F401
from .speculative import (  # noqa: F401
    ModelDraft,
    NgramDraft,
    SpeculativeLMEngine,
)
from .queue import RequestQueue  # noqa: F401
from .request import (  # noqa: F401
    AdmissionError,
    DeadlineExceededError,
    OverloadShedError,
    QueueFullError,
    Request,
    SchedulerClosedError,
    ServingError,
)
from .scheduler import (  # noqa: F401
    BackendExecutor,
    DecodeScheduler,
    JitExecutor,
    Scheduler,
)

# -- shared scheduler table (tensor_serving elements with the same key
# coalesce into ONE device batch across pipelines) --------------------------
_shared: Dict[str, Tuple[object, tuple]] = {}
_shared_refs: Dict[str, int] = {}
_shared_lock = threading.Lock()


def get_shared_scheduler(key: str, factory: Callable[[], object],
                         signature: tuple = ()) -> object:
    """Acquire the scheduler registered under ``key`` (creating it via
    ``factory`` on first acquire). ``signature`` guards against two
    elements binding one key to DIFFERENT models — coalescing their
    requests would feed one model the other's traffic."""
    with _shared_lock:
        entry = _shared.get(key)
        if entry is None:
            sched = factory()
            _shared[key] = (sched, signature)
            _shared_refs[key] = 0
        elif entry[1] != signature:
            raise ValueError(
                f"serving key '{key}' already bound to {entry[1]}; "
                f"cannot rebind to {signature}")
        _shared_refs[key] += 1
        return _shared[key][0]


def release_shared_scheduler(key: str) -> None:
    """Release one reference; the last release closes the scheduler."""
    with _shared_lock:
        if key not in _shared:
            return
        _shared_refs[key] -= 1
        if _shared_refs[key] > 0:
            return
        sched, _ = _shared.pop(key)
        _shared_refs.pop(key, None)
    sched.close()
