"""Paged KV-cache allocator: refcounted page pool + prefix registry (L6).

The dense ``ContinuousLMEngine`` gives every slot a full ``max_seq`` KV
cache, so concurrent-stream count is bounded by worst-case sequence
length × slots whatever the traffic actually looks like. The paged
engine (``lm_engine.PagedLMEngine``) instead draws fixed-size **pages**
(``page_size`` positions each) from the pool owned here and addresses
them through per-slot **block tables** — a slot's resident bytes follow
its ACTUAL sequence length, and identical prompt prefixes dedupe across
streams by sharing pages (Hermes' memory-over-kernels framing, arxiv
2409.04249; pages are planner-visible resources per the multi-TPU
profiled-segmentation stance, arxiv 2503.01025).

This module is pure HOST bookkeeping — the device arrays live in the
engine; the pool decides *which* page indices back *which* positions:

* **allocation** — a bounded free list. Exhaustion raises the typed
  :class:`PagePoolExhausted`; the scheduler answers with a typed shed
  (admission) or deadline-aware preempt/restore (mid-decode), never an
  OOM.
* **refcounts + COW** — a page referenced by N block tables has
  refcount N. Writers must hold an EXCLUSIVE page: the engine's
  ``_ensure_writable`` asks :meth:`is_shared` and, for a shared page,
  allocates a fresh one, device-copies the contents, and swaps its
  block-table entry (copy-on-write) — the sibling stream never observes
  the divergence.
* **prefix registry** — completed prompt prefills register their page
  chain under the prompt tokens (LRU-bounded; registry holds its own
  refs). A later admit whose prompt starts with a registered chain
  shares those pages instead of recomputing the prefill
  (``prefix_hits_total``).

Leakcheck contract: every page incref pairs with exactly one decref
(``# pairs-with:`` on both sites); under ``NNS_LEAKCHECK=1`` an engine
or scheduler exit path that drops a block table without releasing its
pages fails the test ledger. Gauges
``nns_serving_kv_{pages_total,pages_used,pages_shared,prefix_hits_total,
preemptions_total}`` render from the collector below on every scrape.
"""
from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..analysis import sanitizer as _san
from ..analysis.sanitizer import named_lock
from ..obs import metrics as obs_metrics
from .request import ServingError


class PagePoolExhausted(ServingError):
    """The pool has no free page for a required allocation. Recoverable
    by policy, not by retry: the scheduler either sheds the request with
    a typed ``MemoryPressureError`` (admission) or preempts a victim's
    pages to host and restores them on readmission (mid-decode)."""


_pools: "weakref.WeakSet" = weakref.WeakSet()


class KVPagePool:
    """Host-side allocator for a fixed pool of KV pages.

    ``pages`` counts USABLE pages; index 0 is additionally reserved as
    the null sink every inactive/garbage write is routed to, so device
    scatters never need a branch. Page indices handed out are in
    ``[1, pages]``.
    """

    def __init__(self, pages: int, page_size: int,
                 name: str = "kv_pool", prefix_capacity: int = 32):
        if pages < 1:
            raise ValueError(f"pages={pages} must be >= 1")
        if page_size < 1 or (page_size & (page_size - 1)):
            raise ValueError(
                f"page_size={page_size} must be a positive power of two")
        self.pages = pages
        self.page_size = page_size
        self.name = name
        self._lock = named_lock(f"KVPagePool._lock:{name}")
        # index 0 = null page (never allocated, never freed)
        self._free: List[int] = list(range(pages, 0, -1))  # guarded-by: _lock
        self._ref: Dict[int, int] = {}                     # guarded-by: _lock
        # prompt-token chain -> (page ids, covered positions); LRU order,
        # registry holds one ref per page it advertises
        self._prefixes: "OrderedDict[Tuple[int, ...], Tuple[List[int], int]]" \
            = OrderedDict()                                # guarded-by: _lock
        self._prefix_capacity = prefix_capacity
        # monotonic counters (guarded-by: _lock)
        self.prefix_hits = 0
        self.cow_copies = 0
        self.preemptions = 0
        self.restores = 0
        _pools.add(self)

    def _dec_locked(self, pages: List[int]) -> List[int]:
        """Decref under the held lock; returns the pages actually
        decref'd (for the caller's leak-ledger notes)."""
        dropped: List[int] = []
        for p in pages:
            if p == 0 or p not in self._ref:
                continue
            self._ref[p] -= 1
            dropped.append(p)
            if self._ref[p] == 0:
                del self._ref[p]
                self._free.append(p)
        return dropped

    # -- allocation ----------------------------------------------------------
    def alloc(self, n: int) -> List[int]:   # pairs-with: release
        """Take ``n`` exclusive pages (refcount 1 each). Under pressure
        the prefix registry gives way first — LRU chains evict until the
        request fits (cached prefixes are an optimization, live streams
        are a contract). Raises the typed :class:`PagePoolExhausted`
        only when eviction cannot help — all-or-nothing, so a partial
        grab never strands pages."""
        got: List[int] = []
        evicted: List[int] = []
        try:
            with self._lock:
                while n > len(self._free) and self._prefixes:
                    _, (pages, _) = self._prefixes.popitem(last=False)
                    evicted.extend(self._dec_locked(pages))
                if n > len(self._free):
                    raise PagePoolExhausted(
                        f"pool '{self.name}': need {n} pages, "
                        f"{len(self._free)} free of {self.pages}")
                got = [self._free.pop() for _ in range(n)]
                for p in got:
                    self._ref[p] = 1
        finally:
            if _san.LEAK:
                for p in evicted:  # pairs-with: retain (register_prefix)
                    _san.note_release("kv_page", f"{self.name}:p{p}")
                for p in got:
                    _san.note_acquire("kv_page", f"{self.name}:p{p}")
        return got

    def retain(self, pages: List[int]) -> None:   # pairs-with: release
        """Share already-allocated pages (one more block table points at
        them); each incref pairs with one :meth:`release` decref."""
        with self._lock:
            for p in pages:
                if p not in self._ref:
                    raise ServingError(
                        f"pool '{self.name}': retain of unallocated page {p}")
                self._ref[p] += 1
        if _san.LEAK:
            for p in pages:
                _san.note_acquire("kv_page", f"{self.name}:p{p}")

    def release(self, pages: List[int]) -> None:
        """Drop one reference per listed page; refcount 0 returns the
        page to the free list. Unknown/null entries are ignored so exit
        paths can pass raw block-table rows."""
        with self._lock:
            freed = self._dec_locked(pages)
        if _san.LEAK:
            for p in freed:
                _san.note_release("kv_page", f"{self.name}:p{p}")

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._ref.get(page, 0)

    def is_shared(self, page: int) -> bool:
        """True when a write to ``page`` must copy-on-write first."""
        with self._lock:
            return self._ref.get(page, 0) > 1

    # -- prefix registry ------------------------------------------------------
    def register_prefix(self, tokens, pages: List[int],
                        covered: int) -> None:
        """Advertise a prefilled prompt's page chain for reuse: ``pages``
        back positions ``[0, covered)`` of ``tokens``. The registry
        holds its own reference per page (released on LRU eviction /
        close) so a retired stream's prefix outlives it."""
        key = tuple(int(t) for t in tokens[:covered])
        if not key or not pages:
            return
        self.retain(pages)  # pairs-with: release (eviction / close)
        evicted: Optional[List[int]] = None
        try:
            with self._lock:
                if key in self._prefixes:
                    old_pages, _ = self._prefixes.pop(key)
                    evicted = old_pages
                self._prefixes[key] = (list(pages), covered)
                self._prefixes.move_to_end(key)
                if len(self._prefixes) > self._prefix_capacity:
                    _, (lru_pages, _) = self._prefixes.popitem(last=False)
                    evicted = (evicted or []) + lru_pages
        except BaseException:
            self.release(pages)  # registration failed: drop our incref
            raise
        if evicted:
            self.release(evicted)

    def lookup_prefix(self, tokens) -> Tuple[List[int], int]:
        """Longest registered chain that prefixes ``tokens``: returns
        ``(pages, covered)`` with a registry-independent reference
        already taken on each page (caller owns it; release on retire),
        or ``([], 0)``. Counts a prefix hit."""
        toks = tuple(int(t) for t in tokens)
        best_key: Optional[Tuple[int, ...]] = None
        best: Tuple[List[int], int] = ([], 0)
        with self._lock:
            for key, (pages, covered) in self._prefixes.items():
                if covered <= len(toks) and covered > best[1] \
                        and toks[:covered] == key:
                    best_key, best = key, (list(pages), covered)
            if best_key is not None:
                self._prefixes.move_to_end(best_key)
                self.prefix_hits += 1
        if best_key is not None:
            self.retain(best[0])  # pairs-with: release (slot retire)
        return best

    def clear_prefixes(self) -> None:
        with self._lock:
            chains = [pages for pages, _ in self._prefixes.values()]
            self._prefixes.clear()
        for pages in chains:
            self.release(pages)

    # -- event counters -------------------------------------------------------
    def note_cow(self) -> None:
        with self._lock:
            self.cow_copies += 1

    def note_preemption(self) -> None:
        with self._lock:
            self.preemptions += 1

    def note_restore(self) -> None:
        with self._lock:
            self.restores += 1

    # -- introspection --------------------------------------------------------
    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        with self._lock:
            return len(self._ref)

    @property
    def shared_pages(self) -> int:
        with self._lock:
            return sum(1 for c in self._ref.values() if c > 1)

    def stats(self) -> dict:
        with self._lock:
            used = len(self._ref)
            return {
                "name": self.name,
                "pages_total": self.pages,
                "pages_used": used,
                "pages_free": len(self._free),
                "pages_shared": sum(1 for c in self._ref.values() if c > 1),
                "page_size": self.page_size,
                "prefix_entries": len(self._prefixes),
                "prefix_hits_total": self.prefix_hits,
                "cow_copies_total": self.cow_copies,
                "preemptions_total": self.preemptions,
                "restores_total": self.restores,
                "occupancy": used / self.pages if self.pages else 0.0,
            }

    def close(self) -> None:
        """Release the registry's references (engine/scheduler exit paths
        release slot-held ones); the leak ledger must read zero after."""
        self.clear_prefixes()
        _pools.discard(self)


# -- metrics collector (scrape-time, weakset pattern of obs/metrics.py) ------

_G_TOTAL = obs_metrics.gauge(
    "nns_serving_kv_pages_total", "KV page-pool capacity", ("pool",))
_G_USED = obs_metrics.gauge(
    "nns_serving_kv_pages_used", "KV pages currently referenced", ("pool",))
_G_SHARED = obs_metrics.gauge(
    "nns_serving_kv_pages_shared",
    "KV pages referenced by more than one block table (prefix sharing)",
    ("pool",))
_G_PREFIX_HITS = obs_metrics.gauge(
    "nns_serving_kv_prefix_hits_total",
    "admits that reused a registered prompt-prefix page chain", ("pool",))
_G_PREEMPT = obs_metrics.gauge(
    "nns_serving_kv_preemptions_total",
    "requests whose pages were evicted to host under memory pressure",
    ("pool",))
_G_COW = obs_metrics.gauge(
    "nns_serving_kv_cow_copies_total",
    "copy-on-write page copies (write into a shared page)", ("pool",))


def _collect_kv(_registry) -> None:
    for g in (_G_TOTAL, _G_USED, _G_SHARED, _G_PREFIX_HITS, _G_PREEMPT,
              _G_COW):
        g.clear()
    for pool in list(_pools):
        try:
            s = pool.stats()
        except Exception:  # noqa: BLE001 - pool mid-close
            continue
        _G_TOTAL.set(s["pages_total"], pool=s["name"])
        _G_USED.set(s["pages_used"], pool=s["name"])
        _G_SHARED.set(s["pages_shared"], pool=s["name"])
        _G_PREFIX_HITS.set(s["prefix_hits_total"], pool=s["name"])
        _G_PREEMPT.set(s["preemptions_total"], pool=s["name"])
        _G_COW.set(s["cow_copies_total"], pool=s["name"])


obs_metrics.register_collector("serving_kv", _collect_kv)
