"""Priority request queue with admission control (L6 serving).

Own design (no reference analog — the reference's only buffering is the
unbounded GstQueue): a bounded priority queue that REFUSES work it cannot
serve within budget. Three admission gates, each a typed error
(``serving/request.py``):

* depth — ``max_depth`` pending requests → :class:`QueueFullError`;
* expired deadline at admission → :class:`DeadlineExceededError`;
* predictive — estimated wait (EWMA of batch service time × queue depth
  ahead, normalized by batch capacity) exceeds the request's remaining
  deadline budget → :class:`DeadlineExceededError` NOW instead of
  executing a result nobody will read;
* overload — when an external controller (the autoscaler at its replica
  ceiling — ``service/autoscaler.py``) has armed
  :meth:`~RequestQueue.set_overload`, requests whose priority is at or
  past the cutoff → :class:`OverloadShedError` (graceful degradation:
  the lowest classes shed typed, the rest keep their p99).

Expired requests still in the queue are shed at pop time (they are
completed with the typed error, never silently dropped).
"""
from __future__ import annotations

import heapq
import itertools
import time
from typing import List, Optional, Tuple

from ..analysis.sanitizer import named_condition, named_lock
from .request import (
    DeadlineExceededError,
    OverloadShedError,
    QueueFullError,
    Request,
)

_tiebreak = itertools.count()


class RequestQueue:
    """Thread-safe bounded priority queue (lower ``priority`` first, FIFO
    within a priority level)."""

    def __init__(self, max_depth: int = 256,
                 est_batch_rows: int = 8,
                 predictive_shed: bool = True,
                 on_shed=None):
        if max_depth < 1:
            raise ValueError(f"max_depth={max_depth} must be >= 1")
        self.max_depth = max_depth
        self.est_batch_rows = max(1, est_batch_rows)
        self.predictive_shed = predictive_shed
        # called (outside the lock) for each request shed at POP time —
        # admission-time sheds raise at the caller instead, so this is
        # the owning scheduler's only signal to account them
        self.on_shed = on_shed
        self._lock = named_lock("RequestQueue._lock")
        self._not_empty = named_condition("RequestQueue._not_empty",
                                          lock=self._lock)
        self._heap: List[Tuple[int, int, Request]] = []  # guarded-by: _lock
        # EWMA of one batch's service time
        self._service_ewma_s = 0.0  # guarded-by: _lock
        # overload cutoff: requests with priority >= this are refused
        # (None = disarmed). Armed/cleared by the autoscaler when the
        # replica set cannot grow past the ceiling.
        self._overload_min_priority: Optional[int] = None  # guarded-by: _lock
        self.shed_full = 0      # guarded-by: _lock
        self.shed_deadline = 0  # guarded-by: _lock
        self.shed_overload = 0  # guarded-by: _lock

    # -- overload hook -------------------------------------------------------
    def set_overload(self, min_priority: int) -> None:
        """Arm graceful shedding: admission refuses requests with
        ``priority >= min_priority`` (LOWER priority values are more
        important) with a typed :class:`OverloadShedError`."""
        with self._lock:
            self._overload_min_priority = int(min_priority)

    def clear_overload(self) -> None:
        with self._lock:
            self._overload_min_priority = None

    def overload_min_priority(self) -> Optional[int]:
        with self._lock:
            return self._overload_min_priority

    # -- service-time feedback ----------------------------------------------
    def observe_service_time(self, batch_s: float) -> None:
        """Scheduler feedback after each executed batch — drives the
        estimated-wait admission gate."""
        with self._lock:
            if self._service_ewma_s == 0.0:
                self._service_ewma_s = batch_s
            else:
                self._service_ewma_s += 0.2 * (batch_s - self._service_ewma_s)

    def estimated_wait_s(self) -> float:
        """Predicted time until a request admitted NOW starts executing:
        batches ahead of it (queue depth / batch capacity) × EWMA batch
        service time. 0.0 until the first batch calibrates the EWMA."""
        with self._lock:
            return self._estimated_wait_locked()

    def _estimated_wait_locked(self) -> float:
        if self._service_ewma_s == 0.0:
            return 0.0
        batches_ahead = (len(self._heap) + self.est_batch_rows - 1) \
            // self.est_batch_rows
        return batches_ahead * self._service_ewma_s

    # -- admission ----------------------------------------------------------
    def put(self, req: Request) -> None:
        """Admit or shed. Raises the typed error AND fails the request's
        future with it, so both the submitting thread and any ``on_done``
        observer see the same outcome."""
        now = time.monotonic()
        with self._lock:
            err: Optional[Exception] = None
            if (self._overload_min_priority is not None
                    and req.priority >= self._overload_min_priority):
                self.shed_overload += 1
                err = OverloadShedError(
                    f"serving at capacity: request {req.id} "
                    f"(priority {req.priority}) shed by the overload guard "
                    f"(cutoff {self._overload_min_priority})")
            elif len(self._heap) >= self.max_depth:
                self.shed_full += 1
                err = QueueFullError(
                    f"serving queue at max_depth={self.max_depth}; "
                    f"request {req.id} shed")
            elif req.expired(now):
                self.shed_deadline += 1
                err = DeadlineExceededError(
                    f"request {req.id} deadline already expired at "
                    "admission")
            elif (self.predictive_shed and req.deadline is not None
                    and now + self._estimated_wait_locked() > req.deadline):
                self.shed_deadline += 1
                err = DeadlineExceededError(
                    f"request {req.id} cannot meet its deadline: estimated "
                    f"queue wait {self._estimated_wait_locked() * 1e3:.1f}ms "
                    "exceeds the remaining budget")
            if err is None:
                heapq.heappush(self._heap,
                               (req.priority, next(_tiebreak), req))
                self._not_empty.notify()
                return
        req.fail(err)
        raise err

    # -- pop ----------------------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> Optional[Request]:
        """Pop the highest-priority live request; expired entries are shed
        (completed with DeadlineExceededError) on the way. None on
        timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        expired: List[Request] = []
        try:
            with self._not_empty:
                while True:
                    now = time.monotonic()
                    while self._heap:
                        _, _, req = self._heap[0]
                        if req.expired(now):
                            heapq.heappop(self._heap)
                            self.shed_deadline += 1
                            expired.append(req)
                            continue
                        heapq.heappop(self._heap)
                        return req
                    if deadline is None:
                        # bounded slices, not an indefinite park: a caller
                        # with no timeout still wakes to re-check (and a
                        # stop/notify can never be missed forever)
                        self._not_empty.wait(0.25)
                    else:
                        remaining = deadline - now
                        if remaining <= 0 or not self._not_empty.wait(remaining):
                            return None
        finally:
            # complete expired futures OUTSIDE the lock: on_done callbacks
            # may re-enter the queue (e.g. a retry submit)
            for req in expired:
                req.fail(DeadlineExceededError(
                    f"request {req.id} deadline expired while queued"))
                if self.on_shed is not None:
                    self.on_shed(req)

    def pop_upto(self, max_rows: int) -> List[Request]:
        """Non-blocking bulk pop: highest-priority live requests until
        their row total reaches ``max_rows`` or the queue empties — one
        lock acquisition for the whole backlog drain (the scheduler's
        batch-formation inner loop), not one per request. Expired entries
        are shed on the way, same contract as :meth:`get`."""
        out: List[Request] = []
        expired: List[Request] = []
        rows = 0
        with self._lock:
            now = time.monotonic()
            while self._heap and rows < max_rows:
                _, _, req = heapq.heappop(self._heap)
                if req.expired(now):
                    self.shed_deadline += 1
                    expired.append(req)
                    continue
                out.append(req)
                rows += req.rows
        for req in expired:
            req.fail(DeadlineExceededError(
                f"request {req.id} deadline expired while queued"))
            if self.on_shed is not None:
                self.on_shed(req)
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)

    def drain(self) -> List[Request]:
        """Remove and return every pending request (scheduler shutdown —
        the caller fails them)."""
        with self._lock:
            pending = [r for _, _, r in self._heap]
            self._heap.clear()
            return pending
