"""Continuous-batching schedulers (L6 serving).

Two loops over the same admission/queue/bucketing machinery:

* :class:`Scheduler` — one-shot models (classification, detection, any
  ``tensor_filter``-style callable): requests coalesce into shape-bucketed
  padded batches (``batcher.py``), one jitted call serves many clients.
* :class:`DecodeScheduler` — iterative LM decode against a slot-based
  engine (``lm_engine.py``): new requests JOIN the running batch between
  decode steps (prefill into a free slot), finished sequences RETIRE
  early and free their slot — the Hermes/Orca-style continuous batching
  loop (arxiv 2409.04249).

Both record per-request metrics (queue wait, batch id, bucket, device
time, ttft, total) and register with ``serving.metrics_snapshot()``.

The executor's **compile-count hook** makes the no-recompile-storm
property testable: ``JitExecutor`` counts XLA traces (the counter lives
in the traced function body, so it increments exactly once per
signature), and steady-state same-bucket traffic must hold it at one.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import context as obs_context
from ..obs import flight as obs_flight
from ..utils.log import logger
from .batcher import Batch, BatchFormer
from .metrics import ServingMetrics, register_scheduler
from .queue import RequestQueue
from .request import (
    AdmissionError,
    MemoryPressureError,
    Request,
    SchedulerClosedError,
    ServingError,
)


def _tensors_nbytes(tensors) -> int:
    return sum(int(getattr(t, "nbytes", 0) or 0) for t in tensors)


def _block_ready(outputs) -> None:
    try:
        import jax

        # nnlint: disable=NNL101 — deliberate: futures may only complete
        # once device results exist, and this block is what the device-time
        # metric measures
        jax.block_until_ready(outputs)
    except (ImportError, TypeError):
        pass  # numpy outputs (host-native executors) are already ready


class JitExecutor:
    """jit-wraps a jax-traceable callable and counts compiles: the
    counter increments inside the traced body, which Python only executes
    when XLA traces a NEW input signature — the compile-count hook the
    bucketing tests assert against."""

    def __init__(self, fn: Callable):
        import jax

        self.fn = fn
        self.compiles = 0
        self._jit = jax.jit(self._traced)

    def _traced(self, *xs):
        self.compiles += 1  # runs at trace time only, once per signature
        out = self.fn(*xs)
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    def __call__(self, *xs):
        return self._jit(*xs)


class BackendExecutor:
    """Route batches through an opened FilterBackend (its own compile
    cache applies — e.g. host-native programs that must not be traced)."""

    def __init__(self, backend):
        self.backend = backend
        self.compiles = 0  # tracked by the backend, not here

    def __call__(self, *xs):
        return tuple(self.backend.invoke(list(xs)))


class Scheduler:
    """One-shot continuous batcher: ``submit()`` from any thread; a
    single loop thread forms bucketed batches and executes them.

    ``fn`` — jax-traceable callable batching over axis 0 (wrapped in a
    :class:`JitExecutor`), or pass a prebuilt ``executor``.
    """

    def __init__(self, fn: Optional[Callable] = None, *,
                 executor=None,
                 bucket_sizes: Sequence[int] = (1, 2, 4, 8),
                 max_wait_s: float = 0.005,
                 idle_linger_s: float = 0.0005,
                 max_depth: int = 256,
                 predictive_shed: bool = True,
                 name: str = "scheduler",
                 autostart: bool = True,
                 memory_guard=None,
                 on_close: Optional[Callable[[], None]] = None):
        if (fn is None) == (executor is None):
            raise ValueError("pass exactly one of fn= or executor=")
        self.executor = executor if executor is not None else JitExecutor(fn)
        # memory admission (obs/memory.py AdmissionGuard): projected
        # request bytes reserve against a watermark at submit and release
        # at completion — a saturated-memory server sheds typed instead
        # of OOM-ing mid-batch. None = no byte gate (default).
        self.memory_guard = memory_guard
        self.former = BatchFormer(bucket_sizes, max_wait_s,
                                  idle_linger_s=idle_linger_s)
        self.queue = RequestQueue(max_depth,
                                  est_batch_rows=self.former.max_bucket,
                                  predictive_shed=predictive_shed,
                                  on_shed=self._on_queue_shed)
        self.metrics = ServingMetrics()
        self._on_close = on_close
        self.name = register_scheduler(name, self)
        # request-latency series for the profiler/SLO plane (the name is
        # final only after registration uniquifies it)
        self.metrics.series = f"serving:{self.name}"
        self._running = threading.Event()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Scheduler":
        if self._thread is not None:
            return self
        self._running.set()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"serving:{self.name}",
                                        daemon=True)
        self._thread.start()
        return self

    def _on_queue_shed(self, req: Request) -> None:
        """A request's deadline expired while queued (shed at pop time —
        queue.py already failed its future with the typed error)."""
        self._release_mem(req)
        self.metrics.record_shed(deadline=True)

    # -- memory admission (obs/memory.py AdmissionGuard) --------------------
    def _projected_bytes(self, req: Request) -> int:
        """What this request will hold resident if admitted (the guard's
        reservation unit). One-shot batching: its input tensors."""
        return _tensors_nbytes(req.tensors)

    def _reserve_mem(self, req: Request) -> None:
        """Reserve the request's projected bytes against the guard's
        watermark; sheds with a typed MemoryPressureError when the
        projection would cross it. No guard = no-op."""
        guard = self.memory_guard
        if guard is None:
            return
        nb = self._projected_bytes(req)
        if not guard.reserve(nb):
            err = MemoryPressureError(
                f"request {req.id} shed: projected serving memory "
                f"({guard.inflight_bytes} + {nb} bytes) would cross the "
                f"{guard.limit_bytes}-byte watermark")
            self.metrics.record_shed(memory=True)
            obs_flight.record("memory", "admission_shed",
                              {"scheduler": self.name, "request": req.id,
                               "bytes": nb})
            req.fail(err)
            raise err
        req.metrics["_mem_reserved"] = nb

    def _release_mem(self, req: Request) -> None:
        nb = req.metrics.pop("_mem_reserved", None)
        if nb is not None and self.memory_guard is not None:
            self.memory_guard.release(nb)

    def _record_done(self, req: Request, failed: bool = False) -> None:
        """Every request exit path funnels here: the memory reservation
        dies with the request, whatever killed it."""
        self._release_mem(req)
        self.metrics.record_request_done(req, failed=failed)

    def close(self) -> None:
        """Stop the loop and fail everything still pending with
        SchedulerClosedError (never silently dropped)."""
        self._closed = True
        self._running.clear()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        err = SchedulerClosedError(f"scheduler {self.name} closed")
        for req in self.queue.drain() + self.former.drain():
            req.fail(err)
            self._record_done(req, failed=True)
        if self._on_close is not None:
            self._on_close()
            self._on_close = None

    # -- submission ---------------------------------------------------------
    def submit(self, tensors: Sequence, priority: int = 0,
               deadline_s: Optional[float] = None,
               on_done: Optional[Callable[[Request], None]] = None,
               trace=None) -> Request:
        """Admit a request (tensors batch over axis 0; a lower priority
        number schedules sooner; ``deadline_s`` is a relative latency
        budget). Raises a typed :class:`AdmissionError` when shed —
        admission control happens HERE, synchronously, so a saturated
        server pushes back instead of buffering unboundedly.

        ``trace`` — the caller's :class:`~...obs.context.TraceContext`
        (query wire / tensor_serving propagation); with tracing on and
        no context supplied, admission mints a fresh root span so direct
        submitters still get request-scoped traces."""
        if self._closed:
            raise SchedulerClosedError(f"scheduler {self.name} is closed")
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        req = Request(tensors, priority=priority, deadline=deadline,
                      on_done=on_done, trace=trace)
        if obs_context.TRACING and trace is None:
            req._span = obs_context.start_span(
                f"serving.request:{self.name}", kind="serving",
                attrs={"request_id": req.id})
            req.trace = req._span.context()
        self.metrics.record_submit()
        self._reserve_mem(req)  # raises typed MemoryPressureError on shed
        try:
            self.queue.put(req)
        except AdmissionError as e:
            from .request import DeadlineExceededError, OverloadShedError

            self._release_mem(req)
            self.metrics.record_shed(
                deadline=isinstance(e, DeadlineExceededError),
                overload=isinstance(e, OverloadShedError))
            raise
        self._fail_if_closed_after_put(req)
        return req

    def _fail_if_closed_after_put(self, req: Request) -> None:
        """close() may have drained the queue between our _closed check
        and queue.put — the request would strand forever. Re-check and
        drain again: if close ran, everything just enqueued (ours
        included) gets the same typed error close() gives."""
        if not self._closed:
            return
        err = SchedulerClosedError(f"scheduler {self.name} closed")
        stranded = self.queue.drain()
        for r in stranded:
            r.fail(err)
            self._record_done(r, failed=True)
        if req in stranded:
            raise err

    def __call__(self, tensors: Sequence, **kw) -> Tuple:
        """Convenience: submit and block for the result."""
        timeout = kw.pop("timeout", 60.0)
        return self.submit(tensors, **kw).result(timeout)

    @property
    def compile_count(self) -> int:
        """XLA compiles the executor has performed (the no-recompile
        assertion hook; meaningful for JitExecutor)."""
        return self.executor.compiles

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap["queue_depth"] = self.queue.depth()
        snap["estimated_wait_ms"] = self.queue.estimated_wait_s() * 1e3
        snap["compile_count"] = self.compile_count
        return snap

    # -- loop ---------------------------------------------------------------
    def _loop(self) -> None:
        while self._running.is_set():
            flush_in = self.former.next_flush_in()
            timeout = 0.05 if flush_in is None else min(flush_in, 0.05)
            req = self.queue.get(timeout=timeout)
            if req is not None:
                self.former.add(req)
                # bulk-drain the backlog — one loop pass forms the
                # largest batch it allows, one lock acquisition for the
                # whole drain instead of one per queued request
                short = self.former.max_bucket - self.former.pending_rows()
                if short > 0:
                    for more in self.queue.pop_upto(short):
                        self.former.add(more)
            for batch in self.former.take_ready(
                    idle=self.queue.depth() == 0):
                self._execute(batch)

    def _execute(self, batch: Batch) -> None:
        t_start = time.monotonic()
        for r in batch.requests:
            r.metrics["queue_wait_s"] = t_start - r.metrics["enqueue_time"]
            r.metrics["batch_id"] = batch.id
            r.metrics["bucket"] = batch.padded_rows
        try:
            inputs = batch.stacked_tensors()
            outputs = self.executor(*inputs)
            _block_ready(outputs)
        except Exception as e:  # noqa: BLE001 - must fail futures, not the loop
            err = e if isinstance(e, ServingError) else ServingError(
                f"batch {batch.id} execution failed: {e}")
            logger.exception("serving %s: batch %d failed", self.name,
                             batch.id)
            obs_flight.record("serving", "batch_failed",
                              {"scheduler": self.name, "batch": batch.id,
                               "error": str(e)[:200]})
            for r in batch.requests:
                r.fail(err)
                self._record_done(r, failed=True)
            return
        device_s = time.monotonic() - t_start
        self.queue.observe_service_time(device_s)
        self.metrics.record_batch(batch.rows, batch.padded_rows, device_s)
        from ..obs import quality as obs_quality

        if obs_quality.ACTIVE:
            # data-plane health tap: sampled batch-output reduction into
            # the "serving:<scheduler>" series (one module-global check
            # when the taps are off)
            obs_quality.observe_outputs(
                f"serving:{self.name}",
                outputs if isinstance(outputs, (list, tuple))
                else (outputs,))
        from ..utils import trace as _trace

        if _trace.ACTIVE:
            _trace.notify_serving(
                "batch", self.name, t_start, device_s,
                {"batch_id": batch.id, "rows": batch.rows,
                 "bucket": batch.padded_rows})
        if obs_context.TRACING:
            # one batch span LINKED to every member request's span — the
            # batch has N parents, which links express and strict
            # parentage cannot (docs/observability.md)
            links = [r.trace for r in batch.requests if r.trace is not None]
            obs_context.record_span(
                f"batch:{self.name}", kind="serving",
                trace_id=links[0].trace_id if links else None,
                links=links, start_s=t_start, dur_s=device_s,
                attrs={"batch_id": batch.id, "rows": batch.rows,
                       "bucket": batch.padded_rows})
        now = time.monotonic()
        for r, outs in zip(batch.requests, batch.split_outputs(outputs)):
            r.metrics["device_time_s"] = device_s
            r.metrics["ttft_s"] = now - r.metrics["enqueue_time"]
            r.metrics.setdefault("total_latency_s",
                                 now - r.metrics["enqueue_time"])
            # record BEFORE complete(): complete() releases the waiter
            # (and the query-bridge answer), so a client must never see
            # its answer while the completed counter still excludes it
            self._record_done(r)
            r.complete(outs)
        # these clients just got results — closed-loop traffic resubmits
        # within the next max-wait window, so hold the idle-boundary
        # flush until that many rows land (or the window lapses) rather
        # than fragmenting the incoming burst into batch-of-1 flushes
        self.former.expect(batch.rows, self.former.max_wait_s)


class DecodeScheduler:
    """Continuous-batching loop for iterative decode: a fixed-slot engine
    steps ALL active sequences in one compiled call; requests join
    between steps (prefill into a free slot) and retire the moment they
    finish (max steps or ``eos_id``), freeing the slot for the next
    queued request — no drain barrier between batches.

    The engine contract (``lm_engine.ContinuousLMEngine`` implements it):

    * ``slots`` — fixed batch capacity;
    * ``admit(slot, tokens, steps) -> int`` — prefill; returns the first
      generated token;
    * ``step() -> np.ndarray (slots,)`` — one decode step over every
      slot (inactive slots compute garbage; the loop ignores them);
    * ``release(slot)`` — slot freed (optional);
    * ``compile_count`` — optional compile hook.

    Optional extensions the paged/speculative engines provide
    (``lm_engine.PagedLMEngine`` / ``speculative.SpeculativeLMEngine``):

    * ``admit_start``/``prefill_tick`` — chunked prefill: admit queues
      the prompt, the loop ingests ONE bounded chunk per pass, so a
      long prompt interleaves with running decode instead of stalling
      the batch;
    * ``step_tokens() -> list[list[int]]`` — burst decode (speculative
      rounds emit 1..K tokens per slot per pass);
    * ``preempt(slot) -> blob``/``restore(slot, blob)`` — deadline-aware
      memory pressure: on ``PagePoolExhausted`` the loop evicts the
      victim with the MOST deadline slack to host and requeues it;
      readmission restores byte-exact — the request is never dropped;
    * ``projected_page_bytes(tokens, steps)`` — the AdmissionGuard
      reserves page-pool bytes instead of dense tensor bytes.

    Page-release invariant: EVERY request exit path — normal retire,
    deadline shed (queued or mid-decode), batch failure, close — goes
    through ``engine.release(slot)``, so page refcounts reach zero
    whatever killed the request (asserted by the NNS_LEAKCHECK ledger).
    """

    def __init__(self, engine, *,
                 max_depth: int = 256,
                 predictive_shed: bool = True,
                 name: str = "decode",
                 autostart: bool = True,
                 memory_guard=None):
        self.engine = engine
        self.memory_guard = memory_guard  # see Scheduler.memory_guard
        self.queue = RequestQueue(max_depth, est_batch_rows=engine.slots,
                                  predictive_shed=predictive_shed,
                                  on_shed=self._on_queue_shed)
        self.metrics = ServingMetrics()
        self.name = register_scheduler(name, self)
        self.metrics.series = f"serving:{self.name}"
        self._active: Dict[int, Request] = {}
        self._prefilling: Dict[int, Request] = {}  # chunked-prefill slots
        self._free: List[int] = list(range(engine.slots))[::-1]
        self._running = threading.Event()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "DecodeScheduler":
        if self._thread is not None:
            return self
        self._running.set()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"serving:{self.name}",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._closed = True
        self._running.clear()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        err = SchedulerClosedError(f"scheduler {self.name} closed")
        # in-flight slots MUST release through the engine (page-release
        # invariant: close is an exit path like any other — without this
        # the pool leaks every page a live request held at shutdown)
        for slot in list(self._active) + list(self._prefilling):
            req = self._active.pop(slot, None) or \
                self._prefilling.pop(slot, None)
            if req is not None:
                req.fail(err)
                self._record_done(req, failed=True)
            self._retire_slot_only(slot)
        for req in self.queue.drain():
            req.fail(err)
            self._record_done(req, failed=True)
        close = getattr(self.engine, "close", None)
        if close is not None:
            close()  # paged engine: drop the prefix registry's page refs

    # -- submission ---------------------------------------------------------
    def submit(self, tokens, steps: int, priority: int = 0,
               deadline_s: Optional[float] = None,
               eos_id: Optional[int] = None,
               on_done: Optional[Callable[[Request], None]] = None,
               trace=None) -> Request:
        """Queue a prompt (1-D int32) for up to ``steps`` generated
        tokens (fewer when ``eos_id`` appears). The result tuple holds
        one (n,) int32 array of generated tokens."""
        if self._closed:
            raise SchedulerClosedError(f"scheduler {self.name} is closed")
        if steps < 1:
            raise ValueError(f"steps={steps} must be >= 1")
        tokens = np.asarray(tokens)
        if tokens.ndim != 1:
            raise ValueError(
                f"decode prompt must be 1-D tokens, got shape {tokens.shape}")
        validate = getattr(self.engine, "validate", None)
        if validate is not None:
            validate(tokens, steps)  # fail fast (e.g. prompt+steps > max_seq)
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        req = Request((tokens,), priority=priority, deadline=deadline,
                      steps=steps, eos_id=eos_id, on_done=on_done,
                      trace=trace)
        if obs_context.TRACING and trace is None:
            req._span = obs_context.start_span(
                f"serving.request:{self.name}", kind="serving",
                attrs={"request_id": req.id})
            req.trace = req._span.context()
        self.metrics.record_submit()
        self._reserve_mem(req)  # raises typed MemoryPressureError on shed
        try:
            self.queue.put(req)
        except AdmissionError as e:
            from .request import DeadlineExceededError, OverloadShedError

            self._release_mem(req)
            self.metrics.record_shed(
                deadline=isinstance(e, DeadlineExceededError),
                overload=isinstance(e, OverloadShedError))
            raise
        self._fail_if_closed_after_put(req)
        return req

    _on_queue_shed = Scheduler._on_queue_shed
    _fail_if_closed_after_put = Scheduler._fail_if_closed_after_put
    _reserve_mem = Scheduler._reserve_mem
    _release_mem = Scheduler._release_mem
    _record_done = Scheduler._record_done

    def _projected_bytes(self, req: Request) -> int:
        """Paged engines reserve PAGES (what the request will actually
        pin in the pool), not dense tensor bytes — the AdmissionGuard
        gate matches the resource that can actually run out."""
        projected = getattr(self.engine, "projected_page_bytes", None)
        if projected is not None and req.steps:
            return projected(int(req.tensors[0].size), int(req.steps))
        return _tensors_nbytes(req.tensors)

    @property
    def compile_count(self) -> int:
        return getattr(self.engine, "compile_count", 0)

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap["queue_depth"] = self.queue.depth()
        snap["estimated_wait_ms"] = self.queue.estimated_wait_s() * 1e3
        snap["active_slots"] = len(self._active)
        snap["slots"] = self.engine.slots
        snap["compile_count"] = self.compile_count
        pool = getattr(self.engine, "pool", None)
        if pool is not None:
            snap["kv_pool"] = pool.stats()
        rate = getattr(self.engine, "acceptance_rate", None)
        if rate is not None:
            snap["spec_acceptance_rate"] = rate()
            snap["spec_rounds"] = self.engine.spec_rounds
            snap["spec_proposed"] = self.engine.spec_proposed
            snap["spec_accepted"] = self.engine.spec_accepted
        return snap

    # -- loop ---------------------------------------------------------------
    def _admit_one(self, req: Request) -> bool:
        """Place a request into a free slot: restore a preempted one,
        queue a chunked prefill, or run the blocking admit. Returns
        False when the pool cannot take it YET (request requeued; stop
        admitting this pass)."""
        from .kv_pool import PagePoolExhausted

        slot = self._free.pop()
        t0 = time.monotonic()
        req.metrics.setdefault("queue_wait_s",
                               t0 - req.metrics["enqueue_time"])
        blob = req.metrics.pop("_preempt_blob", None)
        if blob is not None:
            try:
                self.engine.restore(slot, blob)
            except PagePoolExhausted:
                # still too tight: keep it queued, blob intact
                self._free.append(slot)
                req.metrics["_preempt_blob"] = blob
                self._requeue(req)
                return False
            except Exception as e:  # noqa: BLE001 - engine rejected restore
                self._free.append(slot)
                req.fail(e if isinstance(e, ServingError)
                         else ServingError(f"decode restore failed: {e}"))
                self._record_done(req, failed=True)
                return True
            req.metrics["slot"] = slot
            self._active[slot] = req
            self.metrics.record_restore()
            obs_flight.record("memory", "preempt_restore",
                              {"scheduler": self.name, "request": req.id,
                               "slot": slot})
            return True
        if getattr(self.engine, "admit_start", None) is not None:
            try:
                self.engine.admit_start(slot, req.tensors[0], req.steps)
            except PagePoolExhausted:
                self._free.append(slot)
                if not self._preempt_victim():
                    self._fail_mem(req)
                else:
                    self._requeue(req)
                return False
            except Exception as e:  # noqa: BLE001 - engine rejected prompt
                self._free.append(slot)
                req.fail(e if isinstance(e, ServingError)
                         else ServingError(f"decode admit failed: {e}"))
                self._record_done(req, failed=True)
                return True
            req.metrics["slot"] = slot
            req.metrics["_prefill_t0"] = t0
            self._prefilling[slot] = req
            return True
        try:
            first = int(self.engine.admit(slot, req.tensors[0], req.steps))
        except Exception as e:  # noqa: BLE001 - engine rejected this prompt
            self._free.append(slot)
            req.fail(e if isinstance(e, ServingError)
                     else ServingError(f"decode admit failed: {e}"))
            self._record_done(req, failed=True)
            return True
        now = time.monotonic()
        req.metrics["slot"] = slot
        req.metrics["ttft_s"] = now - req.metrics["enqueue_time"]
        req.metrics["prefill_s"] = now - t0
        req.tokens.append(first)
        if self._finished(req, first):
            self._retire(slot, req, early=False)
        else:
            self._active[slot] = req
        return True

    def _requeue(self, req: Request) -> None:
        """Put a preempted/deferred request back in line; if the queue
        itself sheds it, the failure is typed like any admission shed."""
        try:
            self.queue.put(req)
        except AdmissionError as e:
            from .request import DeadlineExceededError

            self.metrics.record_shed(
                deadline=isinstance(e, DeadlineExceededError))
            req.fail(e)
            self._record_done(req, failed=True)

    def _fail_mem(self, req: Request) -> None:
        err = MemoryPressureError(
            f"request {req.id} shed: KV page pool exhausted and no "
            "preemptable victim (typed shed, not an OOM)")
        self.metrics.record_shed(memory=True)
        obs_flight.record("memory", "page_pool_shed",
                          {"scheduler": self.name, "request": req.id})
        req.fail(err)
        self._record_done(req, failed=True)

    def _preempt_victim(self, min_active: int = 1) -> bool:
        """Deadline-aware eviction: push the ACTIVE request with the
        most slack (no deadline beats any deadline; later beats sooner)
        to host and requeue it — never drop it. False when the engine
        cannot preempt or fewer than ``min_active`` streams are running
        (evicting the only runner to feed itself is a livelock, not
        progress — the caller sheds typed instead)."""
        preempt = getattr(self.engine, "preempt", None)
        if preempt is None or len(self._active) < min_active:
            return False
        slot = max(self._active,
                   key=lambda s: (self._active[s].deadline is None,
                                  self._active[s].deadline or 0.0))
        req = self._active.pop(slot)
        try:
            blob = preempt(slot)
        except Exception:  # noqa: BLE001 - engine state is authoritative
            logger.exception("serving %s: preempt of slot %d failed",
                             self.name, slot)
            self._active[slot] = req
            return False
        self._free.append(slot)
        req.metrics["_preempt_blob"] = blob
        self.metrics.record_preemption()
        obs_flight.record("memory", "preemption",
                          {"scheduler": self.name, "request": req.id,
                           "slot": slot,
                           "decoded": len(req.tokens)})
        self._requeue(req)
        return True

    def _finished(self, req: Request, last_token: int) -> bool:
        if len(req.tokens) >= req.steps:
            return True
        return req.eos_id is not None and last_token == req.eos_id

    def _retire(self, slot: int, req: Request, early: bool) -> None:
        self._active.pop(slot, None)
        release = getattr(self.engine, "release", None)
        if release is not None:
            release(slot)
        self._free.append(slot)
        if early:
            self.metrics.record_early_retire()
        req.metrics["decode_steps"] = len(req.tokens)
        # nnlint: disable=NNL101 — req.tokens is a host-side python list;
        # this asarray is a list→array pack, not a device sync
        req.complete((np.asarray(req.tokens, np.int32),))
        self._record_done(req)

    def _prefill_tick(self) -> None:
        """Ingest ONE prompt chunk (chunked-prefill engines): long
        prompts advance one bounded chunk per loop pass, interleaved
        with decode steps, instead of stalling the whole batch."""
        from .kv_pool import PagePoolExhausted

        # bounded retry IN THIS PASS: preempting a victim only helps if
        # the tick reclaims the freed pages before the admit phase
        # restores the victim (otherwise preempt/restore ping-pong
        # forever and the starved prompt never advances)
        done = []
        for _ in range(self.engine.slots + 1):
            try:
                done = self.engine.prefill_tick()
                break
            except PagePoolExhausted:
                if self._preempt_victim():
                    continue
                # no victim left: shed the oldest prefilling request
                # (typed, never an OOM)
                if self._prefilling:
                    slot = next(iter(self._prefilling))
                    req = self._prefilling.pop(slot)
                    self._fail_mem(req)
                    self._retire_slot_only(slot)
                return
            except Exception as e:  # noqa: BLE001 - fail that prompt, keep serving
                logger.exception("serving %s: prefill chunk failed",
                                 self.name)
                if self._prefilling:
                    slot = next(iter(self._prefilling))
                    req = self._prefilling.pop(slot)
                    req.fail(e if isinstance(e, ServingError)
                             else ServingError(f"decode prefill failed: {e}"))
                    self._record_done(req, failed=True)
                    self._retire_slot_only(slot)
                return
        now = time.monotonic()
        for slot, first in done:
            req = self._prefilling.pop(slot, None)
            if req is None:
                continue
            req.metrics["ttft_s"] = now - req.metrics["enqueue_time"]
            req.metrics["prefill_s"] = now - req.metrics.pop(
                "_prefill_t0", now)
            req.tokens.append(int(first))
            if self._finished(req, int(first)):
                self._retire(slot, req, early=False)
            else:
                self._active[slot] = req

    def _shed_expired_active(self) -> None:
        """Mid-decode deadline enforcement: a stream that cannot finish
        in time stops burning slots and steps NOW — and its exit goes
        through the engine release path like every other (pages freed)."""
        now = time.monotonic()
        for slot, req in list(self._active.items()):
            if req.deadline is not None and now > req.deadline:
                from .request import DeadlineExceededError

                req.fail(DeadlineExceededError(
                    f"request {req.id} deadline expired mid-decode "
                    f"after {len(req.tokens)} tokens"))
                self.metrics.record_shed(deadline=True)
                self._record_done(req, failed=True)
                self._retire_slot_only(slot)

    def _loop(self) -> None:
        from .kv_pool import PagePoolExhausted

        has_chunked = getattr(self.engine, "prefill_tick", None) is not None
        step_tokens = getattr(self.engine, "step_tokens", None)
        while self._running.is_set():
            # JOIN: fill free slots from the queue between decode steps —
            # block only when the whole batch is idle
            while self._free:
                busy = self._active or self._prefilling
                req = self.queue.get(timeout=0 if busy else 0.05)
                if req is None:
                    break
                if not self._admit_one(req):
                    break  # pool saturated this pass; retry next pass
            if has_chunked and self._prefilling:
                self._prefill_tick()
            if not self._active:
                continue
            self._shed_expired_active()
            if not self._active:
                continue
            t0 = time.monotonic()
            toks = bursts = None
            stepped = False
            # bounded retry IN THIS PASS (same reasoning as
            # _prefill_tick): after a preemption the survivors must
            # retry the step BEFORE the admit phase restores the victim,
            # or the two sides ping-pong pages forever with zero decode
            # progress. min_active=2 — preempting the only runner to
            # feed itself is that same livelock in one slot.
            for _ in range(self.engine.slots + 1):
                try:
                    if step_tokens is not None:
                        bursts = step_tokens()  # 1..K tokens per slot
                    else:
                        # nnlint: disable=NNL101 — the decode loop's one
                        # designed pull: (slots,) tokens must reach host
                        # to route/retire
                        toks = np.asarray(self.engine.step())
                    stepped = True
                    break
                except PagePoolExhausted:
                    # a running stream crossed into a page the pool
                    # cannot supply: evict the slackest victim and retry
                    # now; if nothing is preemptable the starved stream
                    # sheds typed rather than OOM-ing the device
                    if self._preempt_victim(min_active=2):
                        continue
                    if self._active:
                        slot = next(iter(self._active))
                        req = self._active.pop(slot)
                        self._fail_mem(req)
                        self._retire_slot_only(slot)
                    break
                except Exception as e:  # noqa: BLE001 - fail batch, keep serving
                    err = ServingError(f"decode step failed: {e}")
                    logger.exception("serving %s: decode step failed",
                                     self.name)
                    for slot, req in list(self._active.items()):
                        req.fail(err)
                        self._record_done(req, failed=True)
                        self._retire_slot_only(slot)
                    break
            if not stepped:
                continue
            device_s = time.monotonic() - t0
            self.queue.observe_service_time(device_s)
            self.metrics.record_decode_step(len(self._active),
                                            self.engine.slots, device_s)
            for slot, req in list(self._active.items()):
                burst = ([int(toks[slot])] if bursts is None
                         else [int(t) for t in bursts[slot]])
                req.metrics["device_time_s"] = \
                    req.metrics.get("device_time_s", 0.0) + device_s
                for tok in burst:
                    req.tokens.append(tok)
                    if self._finished(req, tok):
                        # RETIRE early: the slot frees this step, not at
                        # the end of the longest sequence in the batch —
                        # surplus burst tokens past eos/steps are
                        # dropped (cache-consistent: commit already
                        # advanced past them)
                        self._retire(slot, req,
                                     early=len(req.tokens) < req.steps)
                        break

    def _retire_slot_only(self, slot: int) -> None:
        self._active.pop(slot, None)
        release = getattr(self.engine, "release", None)
        if release is not None:
            release(slot)
        self._free.append(slot)
