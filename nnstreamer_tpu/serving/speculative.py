"""Draft-verify speculative decoding over the paged engine (L6).

A decode step is dispatch-bound: one device call yields ONE token per
slot however small the model. Speculative decoding buys back the
dispatch by letting a cheap **draft** propose K-1 tokens and the
**target** score all K positions in ONE ``verify`` call; greedy
acceptance keeps the longest prefix of proposals the target agrees
with, plus the target's own correction token. Because acceptance is
exact-match against the target's argmax, the emitted stream is
**token-identical to target-only decode for ANY acceptance pattern** —
a draft can only change throughput, never output (asserted in
test_kv_paged.py).

Round protocol (carry state: ``tok`` = last emitted token, K/V for it
not yet written; cache valid for positions < ``pos``):

1. draft proposes ``d1..d_{K-1}`` continuing the slot's history;
2. target ``verify`` scores ``[tok, d1..d_{K-1}]`` at positions
   ``pos..pos+K-1`` in one call (writing their K/V);
3. ``j`` = longest prefix with ``argmax(L_{i-1}) == d_i``; emit
   ``d1..dj`` + the correction ``argmax(L_j)`` — 1..K tokens;
4. ``commit`` advances ``pos`` by ``j+1``; rejected positions hold
   garbage K/V that the ``<= pos`` visibility mask hides until decode
   overwrites them.

Drafts: :class:`NgramDraft` (prompt-lookup self-speculation — zero
device cost, the honest CPU-bench winner since CPU decode is
dispatch-bound; wall-clock on real HW is canaried per the
PLACEMENT_r09 stance) and :class:`ModelDraft` (a small transformer
riding the same decoding primitives — the classic (draft, target)
pair that ``service/models.py`` registers per slot). Acceptance-rate
regressions on promote are arbitrated by the PR 11 canary quality gate
(``obs/quality.py:SpecAcceptance``).
"""
from __future__ import annotations

import weakref
from typing import List

import numpy as np

from ..obs import metrics as obs_metrics
from .lm_engine import PagedLMEngine

_engines: "weakref.WeakSet" = weakref.WeakSet()


class NgramDraft:
    """Prompt-lookup draft: propose the continuation that followed the
    most recent earlier occurrence of the current suffix n-gram. No
    parameters, no device work — acceptance is high exactly when the
    output re-uses spans of its own context (the prompt-lookup
    observation), and a miss costs only rejected verify columns."""

    def __init__(self, ngram: int = 3):
        self.ngram = ngram

    def admit(self, slot: int, tokens, first: int) -> None:
        pass  # stateless: history arrives with every propose

    def propose(self, slot: int, hist: List[int], k: int) -> List[int]:
        if k <= 0:
            return []
        h = hist
        for n in range(min(self.ngram, len(h) - 1), 0, -1):
            pat = h[-n:]
            # latest earlier occurrence wins (most recent context)
            for i in range(len(h) - n - 1, -1, -1):
                if h[i:i + n] == pat:
                    out = h[i + n:i + n + k]
                    if out:
                        return (out + [out[-1]] * k)[:k]
        return [h[-1]] * k  # cold fallback: padding the verify columns

    def commit(self, slot: int, emitted: List[int]) -> None:
        pass

    def release(self, slot: int) -> None:
        pass

    def restore(self, slot: int, hist: List[int]) -> None:
        pass


class ModelDraft:
    """Small-transformer draft: per-slot batch-1 dense cache driven by
    the shared decoding primitives. Mirrors the target's carry protocol
    — accepted proposals were the draft's own predictions, so their K/V
    is already correct; a correction just moves the carry, and rejected
    positions stay invisible behind the ``<= pos`` mask.

    The draft prefill compiles once per distinct prompt length (it uses
    the plain dense path); keep prompts bucketed or use NgramDraft where
    that churn matters."""

    def __init__(self, cfg, params):
        import functools

        import jax
        import jax.numpy as jnp

        from ..models.decoding import decode_step, init_cache, prefill

        self.cfg = cfg
        self.params = params
        self._jnp = jnp
        self._cache = {}    # slot -> dense batch-1 cache
        self._pos = {}      # slot -> carry position (= len(history) - 1)
        self._written = {}  # slot -> positions with VALID K/V (count)

        dtype = params["embed"].dtype

        def _prefill(p, tokens):
            cache = init_cache(cfg, 1, dtype=dtype)
            logits, cache, pos = prefill(cfg, p, tokens, cache)
            return cache, pos.astype(jnp.int32)

        self._prefill = jax.jit(_prefill)

        def _step(p, token, pos, cache):
            logits, cache = decode_step(cfg, p, token, pos, cache)
            return jnp.argmax(logits, -1).astype(jnp.int32), cache

        self._step = functools.partial(
            jax.jit(_step, donate_argnums=(3,)), params)
        self._jax = jax

    def _ingest(self, slot: int, token: int, pos: int) -> int:
        nxt, self._cache[slot] = self._step(
            self._jnp.asarray([token], self._jnp.int32),
            self._jnp.asarray(pos, self._jnp.int32), self._cache[slot])
        return int(nxt[0])

    def admit(self, slot: int, tokens, first: int) -> None:
        toks = self._jnp.asarray(np.asarray(tokens, np.int32)[None, :])
        self._cache[slot], pos = self._prefill(self.params, toks)
        self._pos[slot] = int(pos)
        self._written[slot] = int(pos)

    def propose(self, slot: int, hist: List[int], k: int) -> List[int]:
        if slot not in self._cache or k <= 0:
            return []
        pos = self._pos[slot]  # == len(hist) - 1, the carry's position
        # catch-up: a fully-accepted round leaves the last accepted
        # token's K/V unwritten (the target wrote it, we never stepped
        # it) — replay it from the authoritative history
        while self._written[slot] < pos:
            w = self._written[slot]
            self._ingest(slot, int(hist[w]), w)
            self._written[slot] = w + 1
        tok = int(hist[-1])
        out: List[int] = []
        for i in range(k):
            if pos + i >= self.cfg.max_seq:
                break
            tok = self._ingest(slot, tok, pos + i)
            self._written[slot] = max(self._written[slot], pos + i + 1)
            out.append(tok)
        return out

    def commit(self, slot: int, emitted: List[int]) -> None:
        # accepted proposals were the draft's own predictions, so their
        # K/V is already correct; everything past the correction point
        # is STALE (it was written for a rejected prediction) — roll the
        # validity watermark back so propose() replays it from history
        if slot in self._pos and emitted:
            self._written[slot] = min(self._written[slot],
                                      self._pos[slot] + len(emitted))
            self._pos[slot] += len(emitted)

    def release(self, slot: int) -> None:
        self._cache.pop(slot, None)
        self._pos.pop(slot, None)
        self._written.pop(slot, None)

    def restore(self, slot: int, hist: List[int]) -> None:
        # re-derive draft state from the authoritative history:
        # cache = prefill(hist[:-1]), carry = hist[-1]
        self.admit(slot, hist[:-1], int(hist[-1]))


class SpeculativeLMEngine:
    """Scheduler-facing wrapper pairing a :class:`PagedLMEngine` target
    with a draft. Implements the engine contract plus ``step_tokens()``
    — the multi-token-per-pass path ``DecodeScheduler`` prefers when
    present. ``step()`` stays available and speculative, returning only
    each slot's first emitted token (contract shim for callers that
    cannot consume bursts)."""

    def __init__(self, target: PagedLMEngine, draft, k: int = 4):
        if k < 2:
            raise ValueError(f"k={k} must be >= 2 (1 carry + proposals)")
        self.target = target
        self.draft = draft
        self.k = k
        self._hist: "dict[int, List[int]]" = {}
        # acceptance accounting (scraped by the collector below and fed
        # to the obs/quality SpecAcceptance gate on canary promote)
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        _engines.add(self)

    # -- contract delegation --------------------------------------------------
    @property
    def cfg(self):
        return self.target.cfg

    @property
    def slots(self) -> int:
        return self.target.slots

    @property
    def compile_count(self) -> int:
        return self.target.compile_count

    @property
    def active_slots(self) -> int:
        return self.target.active_slots

    @property
    def pool(self):
        return self.target.pool

    def validate(self, tokens, steps) -> None:
        self.target.validate(tokens, steps)

    def projected_page_bytes(self, tokens: int, steps: int) -> int:
        return self.target.projected_page_bytes(tokens, steps)

    def memory_bytes(self) -> dict:
        out = dict(self.target.memory_bytes())
        # rides the target's row in obs top's SERVING section: occupancy
        # and acceptance answer "is speculation paying for its pages?"
        out["spec_acceptance_rate"] = self.acceptance_rate()
        return out

    def admit_start(self, slot: int, tokens, steps: int) -> None:
        self.target.admit_start(slot, tokens, steps)
        self._hist[slot] = [int(t) for t in np.asarray(tokens).ravel()]

    def prefill_tick(self):
        done = self.target.prefill_tick()
        for slot, first in done:
            self._hist[slot].append(int(first))
            self.draft.admit(slot, self._hist[slot][:-1], int(first))
        return done

    def admit(self, slot: int, tokens, steps: int) -> int:
        self.admit_start(slot, tokens, steps)
        while True:
            for s, first in self.prefill_tick():
                if s == slot:
                    return first

    def release(self, slot: int) -> None:
        self.target.release(slot)
        self.draft.release(slot)
        self._hist.pop(slot, None)

    def preempt(self, slot: int) -> dict:
        blob = self.target.preempt(slot)
        blob["hist"] = list(self._hist.get(slot, []))
        self.draft.release(slot)
        return blob

    def restore(self, slot: int, blob: dict) -> None:
        self.target.restore(slot, blob)
        self._hist[slot] = list(blob.get("hist", []))
        if self._hist[slot]:
            self.draft.restore(slot, self._hist[slot])

    # -- the speculative round ------------------------------------------------
    def step_tokens(self) -> List[List[int]]:
        """One draft-verify round over every slot → per-slot emitted
        token bursts (1..k tokens active, [] inactive). May raise
        PagePoolExhausted exactly like ``step()``."""
        t = self.target
        active = np.flatnonzero(t._mask)
        out: List[List[int]] = [[] for _ in range(t.slots)]
        if active.size == 0:
            return out
        K = self.k
        mat = np.zeros((t.slots, K), np.int32)
        for s in active:
            s = int(s)
            mat[s, 0] = t._tok[s, 0]
            props = self.draft.propose(s, self._hist[s], K - 1)
            props = (props + [mat[s, 0]] * (K - 1))[:K - 1]
            mat[s, 1:] = props
        # fused verify + greedy acceptance + carry advance in ONE device
        # call: emitted tokens are the target's own argmax prefix, so the
        # round's host traffic is the mat upload and two tiny int pulls
        pred, n_emit = t.verify_commit(mat)
        for s in active:
            s = int(s)
            n = int(n_emit[s])
            if not n:
                continue
            emitted = [int(x) for x in pred[s, :n]]
            self.spec_rounds += 1
            self.spec_proposed += K - 1
            self.spec_accepted += n - 1
            self.draft.commit(s, emitted)
            self._hist[s].extend(emitted)
            out[s] = emitted
        return out

    def step(self) -> np.ndarray:
        """Single-token contract shim: run a speculative round but emit
        only the first token per slot (the rest of the burst is
        discarded host-side — the cache stays consistent because commit
        already advanced past the full acceptance)."""
        burst = self.step_tokens()
        tok = np.zeros((self.slots,), np.int32)
        for s, toks in enumerate(burst):
            if toks:
                tok[s] = toks[0]
        return tok

    def acceptance_rate(self) -> float:
        if not self.spec_proposed:
            return 0.0
        return self.spec_accepted / self.spec_proposed

    def close(self) -> None:
        self.target.close()
        _engines.discard(self)


# -- acceptance gauges (scrape-time, weakset pattern) ------------------------

_G_ROUNDS = obs_metrics.gauge(
    "nns_serving_spec_rounds_total",
    "speculative draft-verify rounds (per slot)", ("pool",))
_G_PROPOSED = obs_metrics.gauge(
    "nns_serving_spec_proposed_total",
    "draft tokens offered for verification", ("pool",))
_G_ACCEPTED = obs_metrics.gauge(
    "nns_serving_spec_accepted_total",
    "draft tokens the target agreed with", ("pool",))
_G_RATE = obs_metrics.gauge(
    "nns_serving_spec_acceptance_rate",
    "accepted / proposed over the engine lifetime", ("pool",))


def _collect_spec(_registry) -> None:
    for g in (_G_ROUNDS, _G_PROPOSED, _G_ACCEPTED, _G_RATE):
        g.clear()
    for eng in list(_engines):
        try:
            name = eng.target._mem_name
            _G_ROUNDS.set(eng.spec_rounds, pool=name)
            _G_PROPOSED.set(eng.spec_proposed, pool=name)
            _G_ACCEPTED.set(eng.spec_accepted, pool=name)
            _G_RATE.set(eng.acceptance_rate(), pool=name)
        except Exception:  # noqa: BLE001 - engine mid-close
            continue


obs_metrics.register_collector("serving_spec", _collect_spec)
