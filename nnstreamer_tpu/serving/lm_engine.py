"""Slot-based continuous-decode engine over the LM decoding primitives
(L6 serving ← models/decoding.py).

The batched-generation paths in ``models/lm_serving.py`` decode a FIXED
batch: everyone prefills together, everyone steps together, the batch
drains before the next one forms. Continuous batching needs per-slot
independence — each sequence has its own position and lifetime — which
this engine gets by **vmapping** :func:`models.decoding.decode_step` over
a leading slot axis: one compiled program steps every slot, each against
its own KV cache and position, exactly the math of S independent
batch-1 decoders but issued as ONE device call per token.

Join protocol (driven by ``DecodeScheduler``):

* ``admit(slot, prompt, steps)`` — prefill the prompt in isolation
  (batch-1 cache), then scatter the fresh cache into the slot axis of
  the batched state (one jitted ``.at[slot].set`` per join). Prefill
  compiles once per distinct prompt length — if that recompile churn
  matters for your traffic, use :class:`PagedLMEngine` below: its
  chunked prefill makes the chunk size the ONLY compiled prefill shape,
  so compile_count stays flat across arbitrary prompt lengths.
* ``step()`` — one vmapped decode step over ALL slots. Inactive slots
  compute garbage at position 0 (static shapes are the point); the
  scheduler ignores their outputs and ``admit`` overwrites their state.
* ``release(slot)`` — host bookkeeping only; device state is dead until
  the next admit overwrites it.

Greedy (argmax) decoding only — sampling policy belongs to the caller's
model entry; the scheduler contract is deterministic token streams.
"""
from __future__ import annotations

import itertools
from typing import Optional

import numpy as np

from ..obs import memory as obs_memory
from .request import ServingError

_engine_ids = itertools.count()


class ContinuousLMEngine:
    """Fixed-slot continuous decoder for a transformer config + params
    (build via ``lm_serving._LMServingEntry.make_continuous``)."""

    def __init__(self, cfg, params, slots: int = 4):
        if slots < 1:
            raise ValueError(f"slots={slots} must be >= 1")
        import functools

        import jax
        import jax.numpy as jnp

        from ..models.decoding import decode_step, init_cache, prefill

        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.compile_count = 0
        self._jnp = jnp

        cache_dtype = params["embed"].dtype
        proto = init_cache(cfg, 1, dtype=cache_dtype)
        # batched state: every cache leaf gains a leading slot axis
        self._cache = jax.tree_util.tree_map(
            lambda a: jnp.zeros((slots, *a.shape), a.dtype), proto)
        # host mirrors: authoritative for admit/release bookkeeping and
        # the scheduler's append/retire reads
        self._tok = np.zeros((slots, 1), np.int32)
        self._pos = np.zeros((slots,), np.int32)
        self._mask = np.zeros((slots,), bool)
        # memory accounting (obs/memory.py): the batched slot cache is
        # the serving plane's dominant resident buffer — its footprint
        # is static (fixed slots × max_seq), so one measurement at build
        # time is the truth for the engine's whole lifetime
        self.cache_bytes = obs_memory.tree_nbytes(self._cache)
        self.param_bytes = obs_memory.tree_nbytes(params)
        self._mem_name = f"lm_engine#{next(_engine_ids)}"
        obs_memory.track_serving(self)

        def _prefill(p, tokens):
            self.compile_count += 1  # trace-time only: once per prompt len
            cache = init_cache(cfg, 1, dtype=cache_dtype)
            logits, cache, pos = prefill(cfg, p, tokens, cache)
            return (jnp.argmax(logits, -1).astype(jnp.int32), cache,
                    pos.astype(jnp.int32))

        self._prefill = jax.jit(_prefill)

        def _one_step(p, token, pos, cache):
            logits, cache = decode_step(cfg, p, token, pos, cache)
            return jnp.argmax(logits, -1).astype(jnp.int32), cache

        def _step(p, token, pos, mask, cache):
            self.compile_count += 1  # trace-time only: one step program
            out, cache = jax.vmap(_one_step, in_axes=(None, 0, 0, 0))(
                p, token, pos, cache)
            # advance the carry state ON DEVICE: inactive slots keep
            # their token/position, active slots take the new token and
            # step forward — the host used to do this per token, paying
            # two H2D uploads per decode step (NNL402's finding)
            token = jnp.where(mask[:, None], out, token)
            pos = pos + mask.astype(jnp.int32)
            return out, token, pos, cache

        # donate the whole device carry — token, position, AND the
        # batched cache (each step rewrites them in place; without
        # donation every token holds two full slot-caches in device
        # memory). The mask is NOT donated: it is reused unchanged
        # across steps and only re-uploaded at admit/release.
        self._step = functools.partial(
            jax.jit(_step, donate_argnums=(1, 2, 4)), params)

        def _insert(state, new, slot):
            self.compile_count += 1
            return jax.tree_util.tree_map(
                lambda s, n: s.at[slot].set(n), state, new)

        self._insert = jax.jit(_insert, donate_argnums=(0,))
        self._jax = jax
        # device carry state (tok/pos/mask): resident across decode
        # steps, re-synced from the host mirrors only at admit/release
        # — per-request, not per-token
        self._sync_device_state()

    def _sync_device_state(self) -> None:
        """Re-upload the decode carry state (token/position/mask) from
        the host mirrors. Called at build, admit, and release — the join
        protocol's slot edits — never per token: steady-state decode
        carries these arrays device-resident and donated."""
        jnp = self._jnp
        self._tok_dev = jnp.asarray(self._tok)
        self._pos_dev = jnp.asarray(self._pos)
        self._mask_dev = jnp.asarray(self._mask)

    # -- scheduler contract --------------------------------------------------
    def validate(self, tokens: np.ndarray, steps: int) -> None:
        if tokens.ndim != 1 or tokens.size == 0:
            raise ValueError(
                f"prompt must be non-empty 1-D tokens, got {tokens.shape}")
        if tokens.size + steps > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({tokens.size}) + steps ({steps}) exceeds "
                f"max_seq {self.cfg.max_seq}")

    def admit(self, slot: int, tokens: np.ndarray, steps: int) -> int:
        if self._mask[slot]:
            raise ServingError(f"slot {slot} already active")
        tokens = np.asarray(tokens, np.int32)
        self.validate(tokens, steps)
        first, cache1, pos = self._prefill(self.params, tokens[None, :])
        self._cache = self._insert(self._cache, cache1, slot)
        self._tok[slot, 0] = int(first[0])
        self._pos[slot] = int(pos)
        self._mask[slot] = True
        self._sync_device_state()
        return int(first[0])

    def step(self) -> np.ndarray:
        """One decode step over every slot; returns (slots,) int32 (only
        active-slot entries are meaningful)."""
        tok_dev, self._tok_dev, self._pos_dev, self._cache = self._step(
            self._tok_dev, self._pos_dev, self._mask_dev, self._cache)
        # nnlint: disable=NNL101 — one (slots,) pull per decode step: the
        # scheduler needs host ints to append/retire (documented
        # contract); explicit device_get, so it stays legal under the
        # NNS_XFERCHECK disallow scopes and lands in the byte ledger
        tok = self._jax.device_get(tok_dev)[:, 0]
        self._pos = self._pos + self._mask.astype(np.int32)
        self._tok[self._mask, 0] = tok[self._mask]
        return tok

    def release(self, slot: int) -> None:
        self._mask[slot] = False
        self._tok[slot, 0] = 0
        self._pos[slot] = 0
        self._sync_device_state()

    # -- introspection --------------------------------------------------------
    @property
    def active_slots(self) -> int:
        return int(self._mask.sum())

    def memory_bytes(self) -> dict:
        """Serving-plane byte source (obs/memory.py ``track_serving``
        contract): the slot KV cache + params this engine keeps
        device-resident, and how many slots are live in it."""
        return {"name": self._mem_name, "kind": "kv_cache",
                "bytes": self.cache_bytes,
                "param_bytes": self.param_bytes,
                "slots": self.slots, "active_slots": self.active_slots}


class PagedLMEngine:
    """Block-table paged continuous decoder (the ROADMAP item 4 engine).

    Where :class:`ContinuousLMEngine` gives every slot a dense
    ``max_seq`` cache, this engine draws fixed-size pages from a
    :class:`~.kv_pool.KVPagePool` and addresses them through per-slot
    block tables, gathered/scattered inside the jitted programs:

    * **pool layout** — ``k/v: (layers, pages+1, heads, page, head_dim)``
      device arrays; page 0 is the null sink inactive/pad writes route
      to (no branches in the scatter). A slot's logical position ``p``
      lives at ``(block_table[p // page], p % page)``.
    * **chunked prefill** — ``admit_start`` queues the prompt and
      ``prefill_tick`` ingests ONE fixed-size chunk per call, so a long
      prompt interleaves with running decode instead of stalling the
      batch, and the chunk size is the only compiled prefill shape
      (``compile_count`` is flat across prompt lengths — the NNL008
      churn fix).
    * **COW prefix sharing** — identical prompt prefixes resolve to the
      same pages via the pool's registry; ``_ensure_writable`` copies a
      shared page before any write lands in it, so divergence never
      perturbs the sibling stream.
    * **preempt/restore** — ``preempt`` pulls a slot's pages to host and
      frees them; ``restore`` re-allocates and uploads byte-exact, so
      memory pressure never drops a request.

    Parity contract: masked scores sit at -1e30 → exact-zero softmax
    weight, and the gathered context length equals ``max_seq``, so the
    paged step is token-exact against the dense engine (asserted in
    test_kv_paged.py).
    """

    def __init__(self, cfg, params, slots: int = 4, page_size: int = 16,
                 pages: Optional[int] = None, chunk: int = 32,
                 share_prefixes: bool = True, pool_name: Optional[str] = None):
        if slots < 1:
            raise ValueError(f"slots={slots} must be >= 1")
        page_size = min(page_size, cfg.max_seq)
        if cfg.max_seq % page_size:
            raise ValueError(
                f"max_seq {cfg.max_seq} must divide by page_size {page_size}")
        import functools

        import jax
        import jax.numpy as jnp

        from ..models.decoding import _ffn, _split_heads
        from ..models.transformer import _rmsnorm
        from .kv_pool import KVPagePool

        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.page_size = page_size
        self.blocks_per_slot = cfg.max_seq // page_size
        self.chunk = min(chunk, cfg.max_seq)
        self.share_prefixes = share_prefixes
        self.compile_count = 0
        self._jnp = jnp
        self._jax = jax

        if pages is None:
            pages = slots * self.blocks_per_slot  # dense-equivalent pool
        self._mem_name = pool_name or f"lm_engine#{next(_engine_ids)}"
        self.pool = KVPagePool(pages, page_size, name=self._mem_name)

        cache_dtype = params["embed"].dtype
        L, H, Dh = cfg.layers, cfg.heads, cfg.head_dim
        pool_shape = (L, pages + 1, H, page_size, Dh)  # +1: null page 0
        self._kpool = jnp.zeros(pool_shape, cache_dtype)
        self._vpool = jnp.zeros(pool_shape, cache_dtype)
        NB = self.blocks_per_slot
        ctx = NB * page_size  # == max_seq: dense-identical contraction

        # host mirrors (authoritative; device copies re-synced on change)
        self._bt = np.zeros((slots, NB), np.int32)
        self._tok = np.zeros((slots, 1), np.int32)
        self._pos = np.zeros((slots,), np.int32)
        self._mask = np.zeros((slots,), bool)
        self._pending: "dict[int, dict]" = {}  # slot -> chunked-prefill state

        self.cache_bytes = int(self._kpool.nbytes + self._vpool.nbytes)
        self.page_bytes = int(2 * L * H * page_size * Dh
                              * jnp.dtype(cache_dtype).itemsize)
        self.param_bytes = obs_memory.tree_nbytes(params)
        obs_memory.track_serving(self)

        pg = page_size
        scale = None  # closed over below via jnp.sqrt like decode_step

        def _gather_ctx(pool, li, bt):
            # bt (S, NB) -> (S, H, ctx, Dh); logical position p of slot s
            # is element (s, :, p, :) — identical layout to a dense cache.
            # jnp.take lowers to a cheaper gather than advanced indexing
            # on the CPU backend
            g = jnp.take(pool[li], bt, axis=0)      # (S, NB, H, pg, Dh)
            S = bt.shape[0]
            return g.transpose(0, 2, 1, 3, 4).reshape(S, H, ctx, Dh)

        def _step(p, token, pos, mask, bt, kpool, vpool):
            self.compile_count += 1  # trace-time only: one step program
            S = token.shape[0]
            x = (p["embed"][token[:, 0]]
                 + p["pos"][jnp.clip(pos, 0, cfg.max_seq - 1)]
                 ).astype(jnp.float32)[:, None, :]  # (S,1,D)
            bidx = jnp.clip(pos // pg, 0, NB - 1)
            dest = jnp.where(mask & (pos < cfg.max_seq),
                             bt[jnp.arange(S), bidx], 0)
            offs = pos % pg
            positions = jnp.arange(ctx)
            visible = (positions[None, :] <= pos[:, None])  # (S, ctx)
            for li, blk in enumerate(p["blocks"]):
                h = _rmsnorm(x, blk["ln1"])
                q, k, v = jnp.split(h @ blk["wqkv"], 3, axis=-1)
                q, k, v = (_split_heads(cfg, t) for t in (q, k, v))
                kpool = kpool.at[li, dest, :, offs, :].set(
                    k[:, :, 0, :].astype(kpool.dtype))
                vpool = vpool.at[li, dest, :, offs, :].set(
                    v[:, :, 0, :].astype(vpool.dtype))
                ck = _gather_ctx(kpool, li, bt)
                cv = _gather_ctx(vpool, li, bt)
                att = (q @ ck.transpose(0, 1, 3, 2)) / jnp.sqrt(cfg.head_dim)
                att = jnp.where(visible[:, None, None, :], att, -1e30)
                att = jax.nn.softmax(att, axis=-1)
                o = (att @ cv).transpose(0, 2, 1, 3).reshape(S, 1, cfg.dim)
                x = x + o @ blk["wo"]
                x = x + _ffn(blk, _rmsnorm(x, blk["ln2"]), None, cfg)
            logits = _rmsnorm(x[:, 0], p["out_norm"]) @ p["embed"].T
            out = jnp.argmax(logits, -1).astype(jnp.int32)
            token = jnp.where(mask[:, None], out[:, None], token)
            pos = pos + mask.astype(jnp.int32)
            return out, token, pos, kpool, vpool

        self._step = functools.partial(
            jax.jit(_step, donate_argnums=(1, 2, 5, 6)), params)

        C = self.chunk

        def _prefill_chunk(p, toks, start, n_valid, bt, kpool, vpool):
            # toks (C,) padded; ingest positions start..start+n_valid-1 of
            # ONE slot. C is static — the only compiled prefill shape.
            self.compile_count += 1  # trace-time only: once per engine
            q_pos = start + jnp.arange(C)
            valid = jnp.arange(C) < n_valid
            lp = jnp.clip(q_pos, 0, cfg.max_seq - 1)
            dest = jnp.where(valid, bt[lp // pg], 0)
            offs = lp % pg
            x = (p["embed"][toks] + p["pos"][lp]
                 ).astype(jnp.float32)[None]        # (1, C, D)
            positions = jnp.arange(ctx)
            visible = (positions[None, :] <= q_pos[:, None])  # (C, ctx)
            for li, blk in enumerate(p["blocks"]):
                h = _rmsnorm(x, blk["ln1"])
                q, k, v = jnp.split(h @ blk["wqkv"], 3, axis=-1)
                q, k, v = (_split_heads(cfg, t) for t in (q, k, v))
                kpool = kpool.at[li, dest, :, offs, :].set(
                    k[0].transpose(1, 0, 2).astype(kpool.dtype))
                vpool = vpool.at[li, dest, :, offs, :].set(
                    v[0].transpose(1, 0, 2).astype(vpool.dtype))
                ck = _gather_ctx(kpool, li, bt[None])   # (1, H, ctx, Dh)
                cv = _gather_ctx(vpool, li, bt[None])
                att = (q @ ck.transpose(0, 1, 3, 2)) / jnp.sqrt(cfg.head_dim)
                att = jnp.where(visible[None, None], att, -1e30)
                att = jax.nn.softmax(att, axis=-1)
                o = (att @ cv).transpose(0, 2, 1, 3).reshape(1, C, cfg.dim)
                x = x + o @ blk["wo"]
                x = x + _ffn(blk, _rmsnorm(x, blk["ln2"]), None, cfg)
            logits = _rmsnorm(x[0], p["out_norm"]) @ p["embed"].T  # (C, V)
            return logits, kpool, vpool

        self._prefill_chunk = functools.partial(
            jax.jit(_prefill_chunk, donate_argnums=(5, 6)), params)

        def _copy_page(kpool, vpool, dst, src):
            self.compile_count += 1  # trace-time only: the COW primitive
            return (kpool.at[:, dst].set(kpool[:, src]),
                    vpool.at[:, dst].set(vpool[:, src]))

        self._copy_page = jax.jit(_copy_page, donate_argnums=(0, 1))

        def _gather_pages(kpool, vpool, pages_row):
            # (NB,) page ids -> (L, NB, H, pg, Dh) blobs (preempt read)
            return kpool[:, pages_row], vpool[:, pages_row]

        self._gather_pages = jax.jit(_gather_pages)

        def _scatter_pages(kpool, vpool, dest_row, kblob, vblob):
            return (kpool.at[:, dest_row].set(kblob.astype(kpool.dtype)),
                    vpool.at[:, dest_row].set(vblob.astype(vpool.dtype)))

        self._scatter_pages = jax.jit(_scatter_pages, donate_argnums=(0, 1))

        def _verify(p, toks, pos, mask, bt, kpool, vpool):
            # speculative verification: score K tokens per slot in ONE
            # call — toks (S, K) = [carry, draft...], positions
            # pos..pos+K-1. Writes their K/V (host rolls back rejected
            # positions by simply not advancing pos past them: the
            # <=pos visibility mask hides them until overwritten).
            self.compile_count += 1  # trace-time only: once per K
            S, K = toks.shape
            q_pos = pos[:, None] + jnp.arange(K)[None, :]     # (S, K)
            lp = jnp.clip(q_pos, 0, cfg.max_seq - 1)
            # overflow rows (q_pos >= max_seq) route to the null page so
            # they can never clobber the real tail position
            dest = jnp.where(mask[:, None] & (q_pos < cfg.max_seq),
                             bt[jnp.arange(S)[:, None], lp // pg], 0)
            offs = lp % pg
            x = (p["embed"][toks] + p["pos"][lp]).astype(jnp.float32)
            positions = jnp.arange(ctx)
            visible = (positions[None, None, :] <= q_pos[:, :, None])
            for li, blk in enumerate(p["blocks"]):
                h = _rmsnorm(x, blk["ln1"])
                q, k, v = jnp.split(h @ blk["wqkv"], 3, axis=-1)
                q, k, v = (_split_heads(cfg, t) for t in (q, k, v))
                kpool = kpool.at[li, dest, :, offs, :].set(
                    k.transpose(0, 2, 1, 3).astype(kpool.dtype))
                vpool = vpool.at[li, dest, :, offs, :].set(
                    v.transpose(0, 2, 1, 3).astype(vpool.dtype))
                ck = _gather_ctx(kpool, li, bt)
                cv = _gather_ctx(vpool, li, bt)
                # broadcast-multiply-reduce instead of batched matmul:
                # XLA CPU lowers (S*H) tiny K x ctx GEMMs to per-batch
                # library calls whose fixed cost dwarfs the math; the
                # explicit reduce fuses into one loop (~30% off the
                # whole program at K=4)
                att = ((q[:, :, :, None, :] * ck[:, :, None, :, :]).sum(-1)
                       / jnp.sqrt(cfg.head_dim))
                att = jnp.where(visible[:, None], att, -1e30)
                att = jax.nn.softmax(att, axis=-1)
                o = (att[..., None] * cv[:, :, None, :, :]).sum(3)
                o = o.transpose(0, 2, 1, 3).reshape(S, K, cfg.dim)
                x = x + o @ blk["wo"]
                x = x + _ffn(blk, _rmsnorm(x, blk["ln2"]), None, cfg)
            logits = _rmsnorm(x, p["out_norm"]) @ p["embed"].T  # (S, K, V)
            return logits, kpool, vpool

        self._verify = functools.partial(
            jax.jit(_verify, donate_argnums=(5, 6)), params)

        def _verify_commit(p, toks, pos, tok, mask, bt, kpool, vpool):
            # fused speculative round: verify K tokens AND resolve greedy
            # acceptance + carry advance on device. Greedy acceptance
            # emits the target's own argmax prefix (accepted drafts match
            # it by definition, the correction IS it), so the host needs
            # only (pred, n_emit) — two tiny int pulls, no logits
            # download, no carry re-upload.
            logits, kpool, vpool = _verify(p, toks, pos, mask, bt,
                                           kpool, vpool)
            S, K = toks.shape
            pred = jnp.argmax(logits, -1).astype(jnp.int32)   # (S, K)
            budget = cfg.max_seq - pos                        # emit ceiling
            # accept proposal i (column i+1) while every earlier one
            # matched and the emit budget allows position i+1
            ok = ((toks[:, 1:] == pred[:, :-1])
                  & (jnp.arange(K - 1)[None, :] < (budget - 1)[:, None]))
            j = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
            n_emit = jnp.where(mask & (budget > 0), j + 1, 0)
            last = pred[jnp.arange(S), jnp.maximum(n_emit - 1, 0)]
            tok = jnp.where((n_emit > 0)[:, None], last[:, None], tok)
            pos = pos + n_emit
            # pack [n_emit | pred] into ONE (S, K+1) array: the host does
            # a single tiny pull per round instead of two
            out = jnp.concatenate([n_emit[:, None], pred], axis=1)
            return out, tok, pos, kpool, vpool

        self._verify_commit = functools.partial(
            jax.jit(_verify_commit, donate_argnums=(2, 3, 6, 7)), params)
        self._sync_device_state()

    def _sync_device_state(self) -> None:
        """Re-upload the decode carry from the host mirrors
        (admit/release/preempt edits only — never per token). Block
        tables are NOT device-resident: ``self._bt`` rides into every
        jit call as a numpy arg (the committed-call conversion is ~10x
        cheaper than maintaining a device mirror that page-boundary
        crossings would re-upload mid-decode)."""
        jnp = self._jnp
        self._tok_dev = jnp.asarray(self._tok)
        self._pos_dev = jnp.asarray(self._pos)
        self._mask_dev = jnp.asarray(self._mask)

    # -- page bookkeeping -----------------------------------------------------
    def _ensure_writable(self, slot: int, lo: int, hi: int) -> None:
        """Make blocks covering logical positions [lo, hi) exclusively
        owned by ``slot``: allocate missing pages, COW-copy shared ones.
        Raises PagePoolExhausted (caller sheds or preempts)."""
        if hi <= lo:
            return
        for b in range(lo // self.page_size,
                       (hi - 1) // self.page_size + 1):
            page = int(self._bt[slot, b])
            if page == 0:
                # ownership lands in the block table atomically with the
                # alloc: release(slot) walks _bt on every exit path
                # nnlint: disable=NNL302
                self._bt[slot, b] = self.pool.alloc(1)[0]  # pairs-with: release (slot exit)
            elif self.pool.is_shared(page):
                new = self.pool.alloc(1)[0]  # pairs-with: release (slot exit)
                try:
                    self._kpool, self._vpool = self._copy_page(
                        self._kpool, self._vpool, new, page)
                except BaseException:
                    self.pool.release([new])  # copy failed: page never owned
                    raise
                self.pool.release([page])  # drop OUR ref; sibling keeps its page
                self._bt[slot, b] = new
                self.pool.note_cow()

    def projected_page_bytes(self, tokens: int, steps: int) -> int:
        """Worst-case pool bytes a request needs (no sharing assumed) —
        the AdmissionGuard reservation unit (pages, not dense slots)."""
        n = -(-(tokens + steps) // self.page_size)
        return n * self.page_bytes

    # -- scheduler contract ---------------------------------------------------
    def validate(self, tokens: np.ndarray, steps: int) -> None:
        if tokens.ndim != 1 or tokens.size == 0:
            raise ValueError(
                f"prompt must be non-empty 1-D tokens, got {tokens.shape}")
        if tokens.size + steps > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({tokens.size}) + steps ({steps}) exceeds "
                f"max_seq {self.cfg.max_seq}")

    def admit_start(self, slot: int, tokens: np.ndarray, steps: int) -> None:
        """Queue a prompt for chunked prefill (``prefill_tick`` drives
        it). Shared-prefix pages are mapped in immediately; only the
        uncovered tail is recomputed."""
        if self._mask[slot] or slot in self._pending:
            raise ServingError(f"slot {slot} already active")
        tokens = np.asarray(tokens, np.int32)
        self.validate(tokens, steps)
        covered = 0
        if self.share_prefixes:
            pages, covered = self.pool.lookup_prefix(tokens)
            if pages:
                self._bt[slot, :len(pages)] = pages
                # always recompute >=1 position: the final prompt token's
                # logits seed the first generated token
                covered = min(covered, tokens.size - 1)
        self._pending[slot] = {"tokens": tokens, "next": covered,
                               "steps": steps}

    def prefill_tick(self) -> "list[tuple[int, int]]":
        """Ingest ONE chunk of ONE pending prompt (oldest first);
        returns [(slot, first_token)] when that prompt completes, else
        []. The scheduler calls this once per loop pass so prefill
        interleaves with running decode instead of stalling it."""
        if not self._pending:
            return []
        jnp = self._jnp
        slot = next(iter(self._pending))
        st = self._pending[slot]
        tokens, start = st["tokens"], st["next"]
        n_valid = min(self.chunk, tokens.size - start)
        self._ensure_writable(slot, start, start + n_valid)
        padded = np.zeros((self.chunk,), np.int32)
        padded[:n_valid] = tokens[start:start + n_valid]
        logits, self._kpool, self._vpool = self._prefill_chunk(
            jnp.asarray(padded), jnp.asarray(start, jnp.int32),
            jnp.asarray(n_valid, jnp.int32), self._bt[slot],
            self._kpool, self._vpool)
        st["next"] = start + n_valid
        if st["next"] < tokens.size:
            return []
        # prompt complete: seed the decode carry from the last REAL row
        del self._pending[slot]
        first = int(np.argmax(np.asarray(logits[n_valid - 1])))
        self._tok[slot, 0] = first
        self._pos[slot] = tokens.size
        self._mask[slot] = True
        if self.share_prefixes:
            # register FULL pages only: a later prompt sharing just the
            # prefix (not the tail) still hits, and registered pages are
            # immutable — this stream's future writes land at positions
            # >= tokens.size, past every registered page (COW guards the
            # page-aligned case where position size-1 is in the last
            # registered page)
            nb_full = tokens.size // self.page_size
            if nb_full:
                self.pool.register_prefix(
                    tokens,
                    [int(p) for p in self._bt[slot, :nb_full] if p],
                    nb_full * self.page_size)
        self._sync_device_state()
        return [(slot, first)]

    def admit(self, slot: int, tokens: np.ndarray, steps: int) -> int:
        """Blocking admit (contract-compatible with the dense engine):
        runs the chunked prefill to completion before returning."""
        self.admit_start(slot, tokens, steps)
        while slot in self._pending:
            done = self.prefill_tick()
            for s, first in done:
                if s == slot:
                    return first
        raise ServingError(f"slot {slot} prefill did not complete")

    def step(self) -> np.ndarray:
        """One paged decode step over every slot; may raise
        PagePoolExhausted when an active slot crosses into a page the
        pool cannot supply (scheduler preempts a victim and retries)."""
        for s in np.flatnonzero(self._mask):
            if self._pos[s] < self.cfg.max_seq:
                self._ensure_writable(int(s), int(self._pos[s]),
                                      int(self._pos[s]) + 1)
        tok_dev, self._tok_dev, self._pos_dev, self._kpool, self._vpool = \
            self._step(self._tok_dev, self._pos_dev, self._mask_dev,
                       self._bt, self._kpool, self._vpool)
        # nnlint: disable=NNL101 — one (slots,) pull per decode step: the
        # scheduler needs host ints to append/retire (documented
        # contract), matching the dense engine's ledger entry
        tok = self._jax.device_get(tok_dev)
        self._pos = self._pos + self._mask.astype(np.int32)
        self._tok[self._mask, 0] = tok[self._mask]
        return tok

    def verify(self, draft: np.ndarray) -> np.ndarray:
        """Score ``draft`` (slots, K) token blocks in one call → logits
        (slots, K, vocab). Column 0 must be each slot's carry token;
        columns 1.. are proposals. Used by SpeculativeLMEngine."""
        K = draft.shape[1]
        for s in np.flatnonzero(self._mask):
            lo = int(self._pos[s])
            self._ensure_writable(int(s), lo,
                                  min(lo + K, self.cfg.max_seq))
        logits, self._kpool, self._vpool = self._verify(
            np.ascontiguousarray(draft, np.int32), self._pos_dev,
            self._mask_dev, self._bt, self._kpool, self._vpool)
        # nnlint: disable=NNL101 — one (slots, K, V) pull per speculative
        # round (K tokens' worth), replacing K per-token pulls
        return self._jax.device_get(logits)

    def verify_commit(self, draft: np.ndarray):
        """Fused speculative round: verify ``draft`` (slots, K) AND
        resolve greedy acceptance + carry advance on device in ONE call.
        Returns ``(pred, n_emit)`` — slot ``s`` emitted
        ``pred[s, :n_emit[s]]`` (accepted drafts equal the target argmax
        by definition; the last entry is the correction). The carry
        stays device-resident: no logits download, no ``commit`` /
        ``sync_carry`` re-upload — the per-round host traffic that
        dominated the unfused path."""
        K = draft.shape[1]
        for s in np.flatnonzero(self._mask):
            lo = int(self._pos[s])
            self._ensure_writable(int(s), lo,
                                  min(lo + K, self.cfg.max_seq))
        # np array passed straight to the jit call: the committed-call
        # conversion is ~10x cheaper than a standalone jnp.asarray
        (packed, self._tok_dev, self._pos_dev,
         self._kpool, self._vpool) = self._verify_commit(
            np.ascontiguousarray(draft, np.int32), self._pos_dev,
            self._tok_dev, self._mask_dev, self._bt,
            self._kpool, self._vpool)
        # nnlint: disable=NNL101 — ONE (slots, K+1) int pull per
        # speculative round (the emitted burst), replacing the (slots,
        # K, V) logits pull of the unfused path
        packed = self._jax.device_get(packed)
        n_emit, pred = packed[:, 0], packed[:, 1:]
        for s in np.flatnonzero(n_emit):
            n = int(n_emit[s])
            self._pos[s] += n
            self._tok[s, 0] = int(pred[s, n - 1])
        return pred, n_emit

    def commit(self, slot: int, tokens: "list[int]",
               sync: bool = True) -> None:
        """Advance a slot past ``tokens`` accepted by speculative
        verification: K/V for them is already in the pool (written by
        ``verify``); only the host carry moves. The LAST entry is the
        new carry token (its K/V is NOT yet written). ``sync=False``
        defers the device upload — the caller batches many slots'
        commits into ONE :meth:`sync_carry` per round (per-slot uploads
        would cost more than the verify call they follow)."""
        if not tokens:
            return
        # verify wrote K/V for [carry, accepted...]: len(tokens)
        # positions are now cache-valid, the new carry's K/V is not
        self._pos[slot] = int(self._pos[slot]) + len(tokens)
        self._tok[slot, 0] = int(tokens[-1])
        if sync:
            self.sync_carry()

    def sync_carry(self) -> None:
        """Upload the host carry mirrors (token + position) in one
        round-trip; pairs with ``commit(..., sync=False)`` batches."""
        self._tok_dev = self._jnp.asarray(self._tok)
        self._pos_dev = self._jnp.asarray(self._pos)

    def release(self, slot: int) -> None:
        self._pending.pop(slot, None)
        self.pool.release([int(p) for p in self._bt[slot] if p])  # pairs-with: alloc/ref (admit path)
        self._bt[slot] = 0
        self._mask[slot] = False
        self._tok[slot, 0] = 0
        self._pos[slot] = 0
        self._sync_device_state()

    # -- preemption -----------------------------------------------------------
    def preempt(self, slot: int) -> dict:
        """Evict a slot to host: pull its pages, free them, deactivate.
        The returned blob restores the request byte-exact later —
        deadline-aware memory pressure never DROPS work (contract with
        the scheduler + obs/memory watermark events)."""
        if not self._mask[slot]:
            raise ServingError(f"slot {slot} not active")
        used = self._bt[slot] != 0
        kblob, vblob = self._gather_pages(
            self._kpool, self._vpool, self._bt[slot])
        # nnlint: disable=NNL101 — preemption IS the host transfer: the
        # victim's pages move to host RAM so the pool can be re-used;
        # restore uploads the same bytes
        blob = {"k": self._jax.device_get(kblob),
                "v": self._jax.device_get(vblob),
                "used": used.copy(), "tok": int(self._tok[slot, 0]),
                "pos": int(self._pos[slot])}
        self.pool.release([int(p) for p in self._bt[slot] if p])  # pairs-with: alloc/ref (admit path)
        self._bt[slot] = 0
        self._mask[slot] = False
        self._sync_device_state()
        self.pool.note_preemption()
        return blob

    def restore(self, slot: int, blob: dict) -> None:
        """Re-admit a preempted request: fresh pages, byte-exact upload,
        decode resumes mid-sequence. Raises PagePoolExhausted if the
        pool still cannot hold it (scheduler keeps it queued)."""
        if self._mask[slot]:
            raise ServingError(f"slot {slot} already active")
        used = blob["used"]
        fresh = self.pool.alloc(int(used.sum()))  # pairs-with: release (slot exit)
        row = np.zeros_like(self._bt[slot])
        row[used] = fresh
        self._bt[slot] = row
        dest = self._jnp.asarray(row)
        self._kpool, self._vpool = self._scatter_pages(
            self._kpool, self._vpool, dest,
            self._jnp.asarray(blob["k"]), self._jnp.asarray(blob["v"]))
        self._tok[slot, 0] = blob["tok"]
        self._pos[slot] = blob["pos"]
        self._mask[slot] = True
        self._sync_device_state()
        self.pool.note_restore()

    # -- introspection --------------------------------------------------------
    @property
    def active_slots(self) -> int:
        return int(self._mask.sum())

    def memory_bytes(self) -> dict:
        """Serving-plane byte source (obs/memory.py ``track_serving``):
        the page pool is the engine's resident buffer; page occupancy
        rides along so obs top can render utilization, not just
        capacity."""
        s = self.pool.stats()
        return {"name": self._mem_name, "kind": "kv_pool",
                "bytes": self.cache_bytes,
                "param_bytes": self.param_bytes,
                "slots": self.slots, "active_slots": self.active_slots,
                "pages_total": s["pages_total"],
                "pages_used": s["pages_used"],
                "pages_shared": s["pages_shared"],
                "page_bytes": self.page_bytes}

    def close(self) -> None:
        for slot in range(self.slots):
            if self._mask[slot] or self._bt[slot].any():
                self.release(slot)
        self.pool.close()


def from_entry(entry, slots: int = 4, mesh=None, paged: bool = False,
               **paged_kw):
    """Build an engine from an ``lm_serving`` entry (params initialized /
    dtype-cast per the entry's serve knobs; ``mesh`` reserved for
    sharded slot state — single-device only today). ``paged=True``
    builds the block-table :class:`PagedLMEngine` (``paged_kw``:
    page_size/pages/chunk/share_prefixes); its executables key into the
    PR 14 AOT cache when ``NNS_AOT_CACHE`` is set."""
    if mesh is not None:
        raise NotImplementedError(
            "continuous decode is single-device today; shard the batch "
            "with the whole-sequence lm_serving paths instead")
    cfg = entry._cfg_serve
    params, _ = entry._shard_params(None)
    if paged:
        import os

        from ..aot import cache as aot_cache

        if os.environ.get(aot_cache.CACHE_ENV):
            # draft AND target executables land in the same persistent
            # XLA cache: a fleet restart replays both without retracing
            aot_cache.attach_xla_cache()
        return PagedLMEngine(cfg, params, slots=slots, **paged_kw)
    return ContinuousLMEngine(cfg, params, slots=slots)
