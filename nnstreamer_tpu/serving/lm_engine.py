"""Slot-based continuous-decode engine over the LM decoding primitives
(L6 serving ← models/decoding.py).

The batched-generation paths in ``models/lm_serving.py`` decode a FIXED
batch: everyone prefills together, everyone steps together, the batch
drains before the next one forms. Continuous batching needs per-slot
independence — each sequence has its own position and lifetime — which
this engine gets by **vmapping** :func:`models.decoding.decode_step` over
a leading slot axis: one compiled program steps every slot, each against
its own KV cache and position, exactly the math of S independent
batch-1 decoders but issued as ONE device call per token.

Join protocol (driven by ``DecodeScheduler``):

* ``admit(slot, prompt, steps)`` — prefill the prompt in isolation
  (batch-1 cache), then scatter the fresh cache into the slot axis of
  the batched state (one jitted ``.at[slot].set`` per join). Prefill
  compiles once per distinct prompt length — bucket prompt lengths
  upstream if that matters for your traffic.
* ``step()`` — one vmapped decode step over ALL slots. Inactive slots
  compute garbage at position 0 (static shapes are the point); the
  scheduler ignores their outputs and ``admit`` overwrites their state.
* ``release(slot)`` — host bookkeeping only; device state is dead until
  the next admit overwrites it.

Greedy (argmax) decoding only — sampling policy belongs to the caller's
model entry; the scheduler contract is deterministic token streams.
"""
from __future__ import annotations

import itertools
from typing import Optional

import numpy as np

from ..obs import memory as obs_memory
from .request import ServingError

_engine_ids = itertools.count()


class ContinuousLMEngine:
    """Fixed-slot continuous decoder for a transformer config + params
    (build via ``lm_serving._LMServingEntry.make_continuous``)."""

    def __init__(self, cfg, params, slots: int = 4):
        if slots < 1:
            raise ValueError(f"slots={slots} must be >= 1")
        import functools

        import jax
        import jax.numpy as jnp

        from ..models.decoding import decode_step, init_cache, prefill

        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.compile_count = 0
        self._jnp = jnp

        cache_dtype = params["embed"].dtype
        proto = init_cache(cfg, 1, dtype=cache_dtype)
        # batched state: every cache leaf gains a leading slot axis
        self._cache = jax.tree_util.tree_map(
            lambda a: jnp.zeros((slots, *a.shape), a.dtype), proto)
        # host mirrors: authoritative for admit/release bookkeeping and
        # the scheduler's append/retire reads
        self._tok = np.zeros((slots, 1), np.int32)
        self._pos = np.zeros((slots,), np.int32)
        self._mask = np.zeros((slots,), bool)
        # memory accounting (obs/memory.py): the batched slot cache is
        # the serving plane's dominant resident buffer — its footprint
        # is static (fixed slots × max_seq), so one measurement at build
        # time is the truth for the engine's whole lifetime
        self.cache_bytes = obs_memory.tree_nbytes(self._cache)
        self.param_bytes = obs_memory.tree_nbytes(params)
        self._mem_name = f"lm_engine#{next(_engine_ids)}"
        obs_memory.track_serving(self)

        def _prefill(p, tokens):
            self.compile_count += 1  # trace-time only: once per prompt len
            cache = init_cache(cfg, 1, dtype=cache_dtype)
            logits, cache, pos = prefill(cfg, p, tokens, cache)
            return (jnp.argmax(logits, -1).astype(jnp.int32), cache,
                    pos.astype(jnp.int32))

        self._prefill = jax.jit(_prefill)

        def _one_step(p, token, pos, cache):
            logits, cache = decode_step(cfg, p, token, pos, cache)
            return jnp.argmax(logits, -1).astype(jnp.int32), cache

        def _step(p, token, pos, mask, cache):
            self.compile_count += 1  # trace-time only: one step program
            out, cache = jax.vmap(_one_step, in_axes=(None, 0, 0, 0))(
                p, token, pos, cache)
            # advance the carry state ON DEVICE: inactive slots keep
            # their token/position, active slots take the new token and
            # step forward — the host used to do this per token, paying
            # two H2D uploads per decode step (NNL402's finding)
            token = jnp.where(mask[:, None], out, token)
            pos = pos + mask.astype(jnp.int32)
            return out, token, pos, cache

        # donate the whole device carry — token, position, AND the
        # batched cache (each step rewrites them in place; without
        # donation every token holds two full slot-caches in device
        # memory). The mask is NOT donated: it is reused unchanged
        # across steps and only re-uploaded at admit/release.
        self._step = functools.partial(
            jax.jit(_step, donate_argnums=(1, 2, 4)), params)

        def _insert(state, new, slot):
            self.compile_count += 1
            return jax.tree_util.tree_map(
                lambda s, n: s.at[slot].set(n), state, new)

        self._insert = jax.jit(_insert, donate_argnums=(0,))
        self._jax = jax
        # device carry state (tok/pos/mask): resident across decode
        # steps, re-synced from the host mirrors only at admit/release
        # — per-request, not per-token
        self._sync_device_state()

    def _sync_device_state(self) -> None:
        """Re-upload the decode carry state (token/position/mask) from
        the host mirrors. Called at build, admit, and release — the join
        protocol's slot edits — never per token: steady-state decode
        carries these arrays device-resident and donated."""
        jnp = self._jnp
        self._tok_dev = jnp.asarray(self._tok)
        self._pos_dev = jnp.asarray(self._pos)
        self._mask_dev = jnp.asarray(self._mask)

    # -- scheduler contract --------------------------------------------------
    def validate(self, tokens: np.ndarray, steps: int) -> None:
        if tokens.ndim != 1 or tokens.size == 0:
            raise ValueError(
                f"prompt must be non-empty 1-D tokens, got {tokens.shape}")
        if tokens.size + steps > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({tokens.size}) + steps ({steps}) exceeds "
                f"max_seq {self.cfg.max_seq}")

    def admit(self, slot: int, tokens: np.ndarray, steps: int) -> int:
        if self._mask[slot]:
            raise ServingError(f"slot {slot} already active")
        tokens = np.asarray(tokens, np.int32)
        self.validate(tokens, steps)
        first, cache1, pos = self._prefill(self.params, tokens[None, :])
        self._cache = self._insert(self._cache, cache1, slot)
        self._tok[slot, 0] = int(first[0])
        self._pos[slot] = int(pos)
        self._mask[slot] = True
        self._sync_device_state()
        return int(first[0])

    def step(self) -> np.ndarray:
        """One decode step over every slot; returns (slots,) int32 (only
        active-slot entries are meaningful)."""
        tok_dev, self._tok_dev, self._pos_dev, self._cache = self._step(
            self._tok_dev, self._pos_dev, self._mask_dev, self._cache)
        # nnlint: disable=NNL101 — one (slots,) pull per decode step: the
        # scheduler needs host ints to append/retire (documented
        # contract); explicit device_get, so it stays legal under the
        # NNS_XFERCHECK disallow scopes and lands in the byte ledger
        tok = self._jax.device_get(tok_dev)[:, 0]
        self._pos = self._pos + self._mask.astype(np.int32)
        self._tok[self._mask, 0] = tok[self._mask]
        return tok

    def release(self, slot: int) -> None:
        self._mask[slot] = False
        self._tok[slot, 0] = 0
        self._pos[slot] = 0
        self._sync_device_state()

    # -- introspection --------------------------------------------------------
    @property
    def active_slots(self) -> int:
        return int(self._mask.sum())

    def memory_bytes(self) -> dict:
        """Serving-plane byte source (obs/memory.py ``track_serving``
        contract): the slot KV cache + params this engine keeps
        device-resident, and how many slots are live in it."""
        return {"name": self._mem_name, "kind": "kv_cache",
                "bytes": self.cache_bytes,
                "param_bytes": self.param_bytes,
                "slots": self.slots, "active_slots": self.active_slots}


def from_entry(entry, slots: int = 4,
               mesh=None) -> "ContinuousLMEngine":
    """Build an engine from an ``lm_serving`` entry (params initialized /
    dtype-cast per the entry's serve knobs; ``mesh`` reserved for
    sharded slot state — single-device only today)."""
    if mesh is not None:
        raise NotImplementedError(
            "continuous decode is single-device today; shard the batch "
            "with the whole-sequence lm_serving paths instead")
    cfg = entry._cfg_serve
    params, _ = entry._shard_params(None)
    return ContinuousLMEngine(cfg, params, slots=slots)
