"""Batch formation: shape-bucketed coalescing with a max-wait timer (L6).

Own design around one XLA reality: jit compiles per input signature, so a
batcher that emits whatever row count happens to be pending would trigger
a recompile storm under organic traffic. The former therefore pads every
batch UP to a fixed bucket size (from ``bucket_sizes``) — steady-state
traffic cycles through at most ``len(bucket_sizes)`` signatures per
tensor layout, all compiled once (asserted via the scheduler's
compile-count hook in tests/test_serving.py).

The max-wait timer bounds the latency cost of waiting for a full bucket:
a batch is flushed when (a) it fills its largest bucket, (b) the OLDEST
member has waited ``max_wait_s``, or (c) a member's deadline is about to
pass. Latency-sensitive traffic is never starved to fill the MXU.
"""
from __future__ import annotations

import itertools
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .request import Request

_batch_ids = itertools.count()


class Batch:
    """A formed batch: ``requests`` contributing ``rows`` real rows,
    padded to ``padded_rows`` (the bucket)."""

    __slots__ = ("id", "requests", "rows", "padded_rows", "bucket_key",
                 "formed_time")

    def __init__(self, requests: List[Request], rows: int, padded_rows: int,
                 bucket_key: tuple):
        self.id = next(_batch_ids)
        self.requests = requests
        self.rows = rows
        self.padded_rows = padded_rows
        self.bucket_key = bucket_key
        self.formed_time = time.monotonic()

    def stacked_tensors(self) -> Tuple[np.ndarray, ...]:
        """Concatenate member rows along axis 0 and zero-pad to the
        bucket — the arrays handed to the device."""
        n_tensors = len(self.requests[0].tensors)
        out = []
        for ti in range(n_tensors):
            parts = [np.asarray(r.tensors[ti]) for r in self.requests]
            # dimensionless scalars batch as rows of shape ()
            parts = [p[None] if p.ndim == 0 else p for p in parts]
            a = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
            pad = self.padded_rows - a.shape[0]
            if pad > 0:
                a = np.concatenate(
                    [a, np.zeros((pad, *a.shape[1:]), a.dtype)], axis=0)
            out.append(a)
        return tuple(out)

    def split_outputs(self, outputs: Sequence) -> List[Tuple]:
        """Slice per-request row ranges back out of the batched outputs.
        An output whose leading dim does not match the padded batch (a
        model that reduces away the batch axis) is replicated to every
        member — the same every-consumer-sees-it semantics a broadcast
        scalar has."""
        per_request: List[List] = [[] for _ in self.requests]
        for out in outputs:
            a = np.asarray(out)
            if a.ndim >= 1 and a.shape[0] == self.padded_rows:
                start = 0
                for i, r in enumerate(self.requests):
                    per_request[i].append(a[start:start + r.rows])
                    start += r.rows
            else:
                for i in range(len(self.requests)):
                    per_request[i].append(a)
        return [tuple(p) for p in per_request]


class _Pending:
    __slots__ = ("requests", "rows", "oldest", "newest")

    def __init__(self):
        self.requests: List[Request] = []
        self.rows = 0
        self.oldest: Optional[float] = None
        self.newest: Optional[float] = None


class BatchFormer:
    """Coalesce compatible requests into shape-bucketed batches.

    ``bucket_sizes`` — ascending row counts a batch may be padded to
    (the jit signatures the device will ever see, per tensor layout).
    ``max_wait_s`` — flush budget for a partially-filled bucket.
    ``idle_linger_s`` — under DENSE traffic (recent inter-arrival EWMA
    below this), an idle-boundary cell is held up to this long after its
    newest member before flushing: a burst of concurrent submitters
    reaches the former one request at a time (GIL / socket scheduling),
    and flushing on the first arrival's bucket boundary would fragment
    the burst into many tiny batches. Sparse traffic (lone client) still
    flushes boundary cells immediately — it pays no linger.
    """

    def __init__(self, bucket_sizes: Sequence[int] = (1, 2, 4, 8),
                 max_wait_s: float = 0.005,
                 idle_linger_s: float = 0.0005):
        sizes = sorted(set(int(b) for b in bucket_sizes))
        if not sizes or sizes[0] < 1:
            raise ValueError(f"bucket_sizes={bucket_sizes!r} must be "
                             "positive integers")
        self.bucket_sizes = tuple(sizes)
        self.max_bucket = sizes[-1]
        self.max_wait_s = max_wait_s
        self.idle_linger_s = idle_linger_s
        self._pending: Dict[tuple, _Pending] = {}
        self._last_add: Optional[float] = None
        self._gap_ewma = float("inf")  # inter-arrival spacing estimate
        self._expect_rows = 0          # scheduler hint: resubmits imminent
        self._expect_until = 0.0

    def bucket_for(self, rows: int) -> int:
        """Smallest configured bucket holding ``rows`` (rows above the
        largest bucket pad to the next multiple of it — an oversized
        request still gets a stable signature)."""
        for b in self.bucket_sizes:
            if rows <= b:
                return b
        mb = self.max_bucket
        return ((rows + mb - 1) // mb) * mb

    def add(self, req: Request) -> None:
        now = time.monotonic()
        if self._last_add is not None:
            gap = now - self._last_add
            if self._gap_ewma == float("inf"):
                self._gap_ewma = gap
            else:
                self._gap_ewma += 0.25 * (gap - self._gap_ewma)
        self._last_add = now
        if self._expect_rows > 0:
            self._expect_rows -= req.rows
        key = req.bucket_key()
        cell = self._pending.get(key)
        if cell is None:
            cell = self._pending[key] = _Pending()
        if not cell.requests:
            cell.oldest = now
        cell.newest = now
        cell.requests.append(req)
        cell.rows += req.rows

    def expect(self, rows: int, window_s: float) -> None:
        """Scheduler hint: results for ``rows`` requests were just
        delivered, so closed-loop clients are about to resubmit — hold
        idle-boundary flushes until those arrivals land (each ``add``
        pays the count down; the flush fires the moment the burst is
        complete) or ``window_s`` lapses, whichever comes first."""
        self._expect_rows = rows
        self._expect_until = time.monotonic() + window_s

    def _expecting_arrivals(self) -> bool:
        """More traffic is likely to land within the linger window, so an
        idle-boundary cell is worth holding. Inside an active expect
        window the outstanding count is authoritative (closed-loop
        clients accounted for exactly); outside it, fall back to the
        inter-arrival density estimate (open-loop streams)."""
        if time.monotonic() < self._expect_until:
            return self._expect_rows > 0
        return self._gap_ewma < self.idle_linger_s

    def pending_rows(self) -> int:
        return sum(c.rows for c in self._pending.values())

    def next_flush_in(self) -> Optional[float]:
        """Seconds until the oldest pending member forces a flush (None =
        nothing pending). The scheduler uses this as its queue-poll
        timeout so a lone request never waits longer than max_wait — or,
        for a boundary cell held by the linger, longer than the linger."""
        expecting = self._expecting_arrivals()
        t_next: Optional[float] = None
        for c in self._pending.values():
            if not c.requests:
                continue
            t = c.oldest + self.max_wait_s
            if expecting and c.rows in self.bucket_sizes:
                t = min(t, c.newest + self.idle_linger_s)
            t_next = t if t_next is None else min(t_next, t)
        if t_next is None:
            return None
        return max(0.0, t_next - time.monotonic())

    def take_ready(self, force: bool = False,
                   idle: bool = False) -> List[Batch]:
        """Pop every batch that is ready: full (>= largest bucket), aged
        past max_wait, or holding a member whose deadline leaves no room
        to keep waiting. ``idle=True`` (the queue behind the former is
        drained) additionally flushes cells sitting exactly ON a bucket
        boundary: padding cost is zero and no co-batchable traffic is
        waiting, so holding them out the max-wait timer buys occupancy
        nothing — it only defers the batch (measured 9× throughput at
        offered-load 1 in tools/bench_serving.py). Under dense traffic
        the boundary flush lingers ``idle_linger_s`` past the newest
        arrival first: concurrent submitters trickle in one at a time,
        and an instant flush would split their burst into fragment
        batches. ``force=True`` flushes everything (shutdown)."""
        now = time.monotonic()
        expecting = self._expecting_arrivals()
        ready: List[Batch] = []
        for key, cell in list(self._pending.items()):
            if not cell.requests:
                del self._pending[key]
                continue
            full = cell.rows >= self.max_bucket
            aged = now - cell.oldest >= self.max_wait_s
            boundary = (idle and cell.rows in self.bucket_sizes
                        and (not expecting
                             or now - cell.newest >= self.idle_linger_s))
            urgent = any(
                r.deadline is not None
                and r.deadline - now <= self.max_wait_s
                for r in cell.requests)
            if not (force or full or aged or boundary or urgent):
                continue
            ready.extend(self._form(key, cell))
            del self._pending[key]
        return ready

    def _form(self, key: tuple, cell: _Pending) -> List[Batch]:
        """Split a pending cell into batches of at most max_bucket rows,
        keeping each request whole (a request's rows never straddle two
        batches — its output slices back out contiguously)."""
        batches: List[Batch] = []
        group: List[Request] = []
        rows = 0
        for r in cell.requests:
            if group and rows + r.rows > self.max_bucket:
                batches.append(Batch(group, rows, self.bucket_for(rows), key))
                group, rows = [], 0
            group.append(r)
            rows += r.rows
        if group:
            batches.append(Batch(group, rows, self.bucket_for(rows), key))
        return batches

    def drain(self) -> List[Request]:
        """Remove and return every pending request (shutdown path)."""
        out: List[Request] = []
        for cell in self._pending.values():
            out.extend(cell.requests)
        self._pending.clear()
        return out
