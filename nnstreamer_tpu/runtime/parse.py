"""gst-launch-style pipeline description parser (L6).

Reference analog: GStreamer's ``gst_parse_launch`` — the reference's primary
UX is text pipelines like::

    videotestsrc ! tensor_converter ! tensor_filter framework=... model=m \
      ! tensor_decoder mode=image_labeling option1=labels.txt ! tensor_sink

Supported syntax subset:
  * ``elem prop=value ...`` — element with properties (values may be quoted);
  * ``a ! b ! c`` — linking;
  * ``name=n`` — naming an element; ``n.`` / ``n.pad`` — link to/from a named
    element (request pads created on demand), e.g. ``t. ! queue ! sink``;
  * ``media/type,field=v,...`` — capsfilter (constrains negotiation);
  * parentheses/bins are not supported (the reference rarely uses them).
"""
from __future__ import annotations

import re
import shlex
from typing import List, Optional, Tuple

from ..core import Caps, Event, EventType, parse_caps_string
from ..core.caps import Structure, looks_like_caps
from .element import TransformElement
from .pad import Pad, PadDirection, PadTemplate
from .pipeline import Pipeline


class CapsFilter(TransformElement):
    """Pass-through element constraining negotiation to its caps (capsfilter)."""

    ELEMENT_NAME = "capsfilter"

    def __init__(self, caps: Caps, name=None):
        media = {s.media_type for s in caps.structures}
        tmpl = Caps(tuple(Structure.new(m) for m in media))
        self.SINK_TEMPLATES = (PadTemplate("sink", PadDirection.SINK, tmpl),)
        self.SRC_TEMPLATES = (PadTemplate("src", PadDirection.SRC, tmpl),)
        super().__init__(name)
        self.filter_caps = caps

    def handle_sink_event(self, pad: Pad, event: Event) -> None:
        if event.type is EventType.CAPS:
            caps = event.data["caps"].intersect(self.filter_caps)
            if caps.is_empty:
                raise ValueError(
                    f"{self.describe()}: caps {event.data['caps']} do not satisfy "
                    f"filter {self.filter_caps}"
                )
            event = Event.caps(caps if caps.is_fixed else caps.fixate())
        super().handle_sink_event(pad, event)

    def transform(self, buf):
        return buf


_NAME_REF_RE = re.compile(r"^(?P<el>[A-Za-z_][\w-]*)\.(?P<pad>[\w%]*)$")


def _pad_links(text: str) -> str:
    """Space-pad '!' link separators, but never inside quoted values
    (a model path like "dir/my!file.py" must survive intact)."""
    out = []
    quote = None
    for ch in text:
        if quote:
            if ch == quote:
                quote = None
            out.append(ch)
        elif ch in "\"'":
            quote = ch
            out.append(ch)
        elif ch == "!":
            out.append(" ! ")
        else:
            out.append(ch)
    return "".join(out)

# One chain entry: ("el", Element) or ("ref", element_name, pad_name|None)
Entry = tuple


def launch_chains(description: str) -> List[List[List[str]]]:
    """Tokenize a launch description into chains of entry token lists.

    This is the pure grammar stage shared by :func:`parse_launch` and the
    static linter's dry checks (``analysis.graph_lint``) — no elements are
    constructed. Each chain is a list of entries; each entry is the token
    list of one element / caps filter / name reference (``["tee",
    "name=t"]``, ``["video/raw,format=RGB"]``, ``["t."]``).
    """
    tokens = shlex.split(_pad_links(description))
    # gst-launch tolerates spaces around '=' in properties and caps
    # fields ("tee name =t", "format = RGB", "width= 100" — all appear in
    # the reference's own runTest corpus): rejoin the fragments. Only
    # unambiguous shapes merge — a bare '=', a token that IS a
    # continuation ("=t"), or a bare "key=" with exactly one '=' (so a
    # VALUE that merely ends with '=' , e.g. base64 padding, never grabs
    # its neighbor).
    fixed: List[str] = []
    i = 0
    while i < len(tokens):
        tok = tokens[i]
        nxt = tokens[i + 1] if i + 1 < len(tokens) else None
        # a bare '=' (or '=value' continuation) can only be a split
        # assignment — merge regardless of the previous token's content
        # ("video/x-raw,width=100,height = 200" must rejoin even though
        # the prior fragment already carries '=' signs)
        if (tok == "=" and fixed and fixed[-1] != "!"
                and nxt is not None and nxt != "!"):
            fixed[-1] += "=" + nxt
            i += 2
            continue
        if tok.startswith("=") and tok != "=" and fixed and fixed[-1] != "!":
            fixed[-1] += tok
            i += 1
            continue
        if (tok.endswith("=") and tok.count("=") == 1 and tok != "="
                and nxt is not None and nxt != "!" and "=" not in nxt):
            # 'key= value' rejoins, but 'option= silent=true' is a
            # deliberately EMPTY value followed by a new assignment — a
            # token carrying its own '=' is never a bare value
            fixed.append(tok + nxt)
            i += 2
            continue
        fixed.append(tok)
        i += 1
    tokens = fixed
    # gst-launch allows spaces after commas inside caps strings
    # ("video/x-raw, width=160, height=120"): a comma-terminated token
    # continues in the next token — but ONLY for tokens that began as a
    # caps string (media/type head), so a property value with a trailing
    # comma (e.g. the reference's option3="0:1:2:3," grammar) is never
    # merged with its neighbor
    caps_head = re.compile(r"^[A-Za-z0-9.+-]+/[A-Za-z0-9.+-]+(,|$)")
    merged: List[str] = []
    for tok in tokens:
        if (merged and merged[-1].endswith(",") and tok != "!"
                and caps_head.match(merged[-1])):
            merged[-1] += tok
        else:
            merged.append(tok)
    tokens = merged

    # Group tokens into entries, entries into chains. Entries within a chain
    # are separated by '!'; a non-property token with no preceding '!' starts
    # a new chain (gst-launch semantics for "tee name=t t. ! ...").
    chains: List[List[List[str]]] = [[]]
    cur: Optional[List[str]] = None
    after_link = True  # pipeline start behaves like after '!'
    for tok in tokens:
        if tok == "!":
            if cur is None:
                raise ValueError("dangling '!' in launch string")
            chains[-1].append(cur)
            cur = None
            after_link = True
        elif cur is None:
            if not after_link and chains[-1]:
                chains.append([])
            cur = [tok]
            after_link = False
        elif "=" in tok:
            cur.append(tok)  # property of the current element
        else:
            chains[-1].append(cur)  # token starts a new chain
            chains.append([])
            cur = [tok]
    if cur is not None:
        chains[-1].append(cur)
    elif after_link and tokens:
        raise ValueError("launch string ends with '!'")
    if not tokens:
        raise ValueError("empty launch string")
    return chains


def parse_launch(description: str, pipeline: Optional[Pipeline] = None,
                 fuse: Optional[bool] = None, place=None) -> Pipeline:
    """Build a Pipeline from a launch string (elements linked, not started).

    Unknown element names raise with a did-you-mean suggestion from the
    registry (``registry.elements.suggest_element`` — the same helper the
    linter's NNL001 rule uses). ``fuse`` and ``place`` forward to the
    Pipeline constructor (device-segment fusion, default on /
    NNS_NO_FUSE env; profile-guided placement, default off /
    ``place="auto"`` / NNS_NO_PLACE kill switch); ignored when an
    existing ``pipeline`` is passed in.
    """
    from ..registry.elements import make_element

    pipe = pipeline or Pipeline(fuse=fuse, place=place)
    chains = launch_chains(description)

    links: List[Tuple[Entry, Entry]] = []
    for chain in chains:
        prev: Optional[Entry] = None
        for entry_tokens in chain:
            entry = _build_entry(entry_tokens, pipe, make_element)
            if prev is not None:
                links.append((prev, entry))
            prev = entry

    for src_ref, sink_ref in links:
        src_pad = _resolve_pad(pipe, src_ref, PadDirection.SRC)
        sink_pad = _resolve_pad(pipe, sink_ref, PadDirection.SINK)
        src_pad.link(sink_pad)

    return pipe


def _build_entry(tokens: List[str], pipe: Pipeline, make_element) -> Entry:
    head = tokens[0]
    m = _NAME_REF_RE.match(head)
    if m and len(tokens) == 1:
        return ("ref", m.group("el"), m.group("pad") or None)
    if looks_like_caps(head):
        caps = parse_caps_string(" ".join(tokens))
        el = CapsFilter(caps)
        pipe.add(el)
        return ("el", el)
    props = {}
    name = None
    for tok in tokens[1:]:
        k, eq, v = tok.partition("=")
        if not eq:
            raise ValueError(f"bad property token '{tok}' for element {head}")
        if k == "name":
            name = v
        else:
            props[k] = v
    el = make_element(head, name=name, **props)
    pipe.add(el)
    return ("el", el)


def _resolve_pad(pipe: Pipeline, ref: Entry, direction: PadDirection) -> Pad:
    if ref[0] == "el":
        return ref[1].get_compatible_pad(direction)
    _, el_name, pad_name = ref
    el = pipe.elements.get(el_name)
    if el is None:
        raise ValueError(f"launch string references unknown element '{el_name}'")
    if pad_name:
        pad = el.get_pad(pad_name)
        if pad is None:
            pad = el.request_pad(direction, pad_name)
        return pad
    return el.get_compatible_pad(direction)
