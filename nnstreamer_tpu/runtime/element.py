"""Element base classes (L0' substrate).

Reference analog: GstElement/GstBaseTransform/GstBaseSrc/GstBaseSink, which
every reference element subclasses (e.g. ``tensor_filter.c:107``
``G_DEFINE_TYPE (..., GST_TYPE_BASE_TRANSFORM)``). GObject properties become a
declarative ``PROPERTIES`` table; caps negotiation is event-driven: when all
sink pads of an element carry fixed caps, the element computes its source caps
(``transform_caps``) and forwards a CAPS event downstream.
"""
from __future__ import annotations

import os
import threading
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..analysis.sanitizer import named_lock
from ..core import Buffer, Caps, Event, EventType, Message, MessageType
from ..utils.log import logger
from .pad import Pad, PadDirection, PadPresence, PadTemplate


@dataclass
class Prop:
    """Declarative element property (GObject property analog)."""

    default: Any = None
    convert: Optional[Callable[[Any], Any]] = None
    doc: str = ""


def prop_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes", "on")


class ElementError(RuntimeError):
    pass


class Element:
    """Base of every pipeline element.

    Subclasses declare:
      * ``ELEMENT_NAME`` — factory name used in launch strings;
      * ``SINK_TEMPLATES`` / ``SRC_TEMPLATES`` — pad templates;
      * ``PROPERTIES`` — launch-string-settable properties;
    and implement ``chain`` (data), optionally ``set_caps``/``transform_caps``
    (negotiation) and ``start``/``stop`` (lifecycle).
    """

    ELEMENT_NAME: str = ""
    SINK_TEMPLATES: Sequence[PadTemplate] = ()
    SRC_TEMPLATES: Sequence[PadTemplate] = ()
    # caps-neutral elements (queue/convert/rate-style) set True so the
    # media shims' downstream capsfilter search (elements/media.py
    # downstream_filter_caps) can look through them
    CAPS_TRANSPARENT: bool = False
    # where this element's steady-state compute runs — the static
    # analyzer's NNL010 rule uses it to spot device→host→device
    # round-trips. "device": runs jitted XLA compute and keeps buffers
    # device-resident (tensor_filter/tensor_serving/tensor_transform);
    # "host": must pull buffers to host memory to do its work
    # (decoders, media converters, sparse codecs); "neutral": works on
    # whatever arrives without forcing a transfer (queues, tees, sinks)
    DEVICE_AFFINITY: str = "neutral"
    # fusion contract (runtime/fusion.py): device-affinity elements are
    # fused into one-dispatch segments by default; STATEFUL device
    # elements whose per-buffer behavior cannot be expressed as a pure
    # traceable function (cross-buffer batching, RNG state) set False
    FUSABLE: bool = True
    # optional class-level barrier message the fusion planner (and the
    # NNL010/NNL013 lint messages) report instead of the generic
    # affinity/FUSABLE reason — e.g. queue's "queue boundary"
    FUSION_BARRIER: Optional[str] = None
    # alternate property spellings (reference/GStreamer names) mapped to
    # the canonical key, applied after dash→underscore normalization
    PROP_ALIASES: Dict[str, str] = {}
    # GStreamer child-proxy syntax ("sink_0::alpha=0.4"): classes that
    # consume per-pad child properties set True; the raw value is stored
    # under the full key for the element to interpret
    ACCEPT_CHILD_PROPS: bool = False
    PROPERTIES: Dict[str, Prop] = {
        # reference: every tensor element carries `silent` (verbose
        # per-buffer logging when false, e.g. gsttensor_converter.c:263)
        "silent": Prop(True, prop_bool, "suppress per-buffer flow logging"),
    }

    _instance_count = 0
    _count_lock = threading.Lock()

    def __init__(self, name: Optional[str] = None, **props):
        cls = type(self)
        # the auto-name carries a PROCESS-global counter, so it is not
        # stable across restarts/replicas — the profiler's canonical
        # naming (obs/profile.py series_name) substitutes a positional
        # alias for auto-named elements
        self.auto_named = name is None
        if name is None:
            with Element._count_lock:
                Element._instance_count += 1
                name = f"{cls.ELEMENT_NAME or cls.__name__.lower()}{Element._instance_count}"
        self.name = name
        self.pipeline = None  # set by Pipeline.add
        self.sink_pads: List[Pad] = []
        self.src_pads: List[Pad] = []
        self._negotiated = False
        # per-instance name: EOS can cascade element-to-element, and two
        # elements' latches must stay distinct lock-order graph nodes
        self._lock = named_lock(f"Element._lock:{name}")
        self._eos_sent = False  # guarded-by: _lock
        # fusion annotations (runtime/fusion.py, set by fusion.install):
        # _fusion_head routes this element's incoming buffers through one
        # fused dispatch; _fusion_member links every segment element for
        # cache invalidation on caps/model changes
        self._fusion_head = None
        self._fusion_member = None
        self.props: Dict[str, Any] = {}
        merged: Dict[str, Prop] = {}
        for klass in reversed(cls.__mro__):
            merged.update(getattr(klass, "PROPERTIES", {}) or {})
        self._prop_defs = merged
        for pname, p in merged.items():
            self.props[pname] = p.default
        for k, v in props.items():
            self.set_property(k, v)
        for tmpl in self.SINK_TEMPLATES:
            if not tmpl.is_request:
                self._add_pad(tmpl, tmpl.name_template)
        for tmpl in self.SRC_TEMPLATES:
            if not tmpl.is_request:
                self._add_pad(tmpl, tmpl.name_template)

    # -- properties ---------------------------------------------------------
    def set_property(self, key: str, value: Any) -> None:
        key = key.replace("-", "_")
        key = self.PROP_ALIASES.get(key, key)
        if "::" in key and self.ACCEPT_CHILD_PROPS:
            self.props[key] = value  # per-pad child property, raw
            return
        if key == "name":
            self.name = str(value)
            return
        if key == "config_file":
            # reference: generic key=value property file, applied in file
            # order at the point the property is set (gst_tensor_parse_
            # config_file, nnstreamer_plugin_api_impl.c:1867; exposed by
            # tensor_decoder and tensor_filter, here by every element)
            self._apply_config_file(str(value))
            self.props["config_file"] = str(value)  # introspectable
            return
        if key not in self._prop_defs:
            raise ElementError(f"{self.describe()}: unknown property '{key}'")
        conv = self._prop_defs[key].convert
        self.props[key] = conv(value) if conv is not None else value

    def _apply_config_file(self, path: str) -> None:
        # cycle guard: a config file naming itself (or a pair naming each
        # other) must fail as an ElementError, not a RecursionError
        real = os.path.realpath(path)
        applying = getattr(self, "_config_files_applying", None)
        if applying is None:
            applying = self._config_files_applying = set()
        if real in applying:
            raise ElementError(
                f"{self.describe()}: config-file cycle via '{path}'")
        try:
            with open(path) as fh:
                lines = fh.read().splitlines()
        except OSError as e:
            raise ElementError(
                f"{self.describe()}: cannot read config-file '{path}': {e}")
        if not applying:  # top-level apply (not a nested config-file line)
            self._config_file_begin()
        applying.add(real)
        try:
            for ln in lines:
                ln = ln.strip()
                if not ln or ln.startswith("#"):
                    continue
                key = ln.split("=", 1)[0].strip().replace("-", "_")
                key = self.PROP_ALIASES.get(key, key)
                if "=" in ln and (key in self._prop_defs
                                  or key in ("name", "config_file")):
                    k, v = ln.split("=", 1)
                    self.set_property(k.strip(), v.strip())
                else:
                    self._config_file_other_line(ln)
        finally:
            applying.discard(real)

    def _config_file_begin(self) -> None:
        """Hook: a fresh top-level config-file apply starts (subclasses
        reset any state accumulated from a previous apply)."""

    def _config_file_other_line(self, ln: str) -> None:
        """Hook for config-file lines that are not known properties.
        Default: unknown ``key=value`` is an error; anything else is
        ignored. tensor_filter overrides to merge into custom options."""
        if "=" in ln:
            self.set_property(*(p.strip() for p in ln.split("=", 1)))

    # elements hosting a subplugin registry set this to their
    # SubpluginKind; the reference's read-only ``sub-plugins`` property
    # (registered subplugin names) is then served here for all of them
    SUBPLUGIN_KIND = None

    def device_affinity(self) -> str:
        """Effective device affinity of THIS instance (classes whose
        affinity depends on configuration — e.g. tensor_src device=true —
        override; everyone else reports DEVICE_AFFINITY)."""
        return self.DEVICE_AFFINITY

    # -- fusion contract (runtime/fusion.py) --------------------------------
    def fusion_barrier(self) -> Optional[str]:
        """Why THIS instance cannot join a fused device segment, or None
        if it is a candidate. Subclasses with per-instance disqualifiers
        (tensor_filter invoke-dynamic/suspend/profiling) extend this."""
        if self.FUSION_BARRIER is not None:
            return self.FUSION_BARRIER
        aff = self.device_affinity()
        if aff != "device":
            return f"{aff}-affinity element"
        if not self.FUSABLE:
            return "FUSABLE=False (stateful element)"
        return None

    def fusion_stage(self):
        """Pure jax-traceable per-buffer transform for segment fusion:
        ``stage(tensors_tuple) -> tensors_tuple``, resolved AFTER caps
        negotiation. None = untraceable right now (the segment falls back
        to per-element dispatch until the next invalidation)."""
        return None

    def fusion_gate(self, buf: Buffer) -> bool:
        """Host-side per-buffer admission for fused dispatch (False =
        drop the buffer, e.g. QoS throttle). Only overrides are invoked —
        pure transform chains pay nothing."""
        return True

    def get_property(self, key: str) -> Any:
        key_n = key.replace("-", "_")
        if key_n == "sub_plugins" and self.SUBPLUGIN_KIND is not None:
            from ..registry.subplugin import names_csv

            return names_csv(self.SUBPLUGIN_KIND)
        return self.props[key_n]

    # -- pads ---------------------------------------------------------------
    def _add_pad(self, tmpl: PadTemplate, name: str) -> Pad:
        pad = Pad(self, tmpl, name)
        (self.sink_pads if tmpl.direction is PadDirection.SINK else self.src_pads).append(pad)
        return pad

    @property
    def sinkpad(self) -> Pad:
        return self.sink_pads[0]

    @property
    def srcpad(self) -> Pad:
        return self.src_pads[0]

    def get_pad(self, name: str) -> Optional[Pad]:
        for p in self.sink_pads + self.src_pads:
            if p.name == name:
                return p
        return None

    def request_pad(self, direction: PadDirection, name: Optional[str] = None) -> Pad:
        """Create an on-demand pad from a REQUEST template ("sink_%u" style)."""
        for tmpl in list(self.SINK_TEMPLATES) + list(self.SRC_TEMPLATES):
            if tmpl.direction is direction and tmpl.is_request:
                existing = self.sink_pads if direction is PadDirection.SINK else self.src_pads
                idx = len([p for p in existing if p.template is tmpl])
                pad_name = name or tmpl.name_template.replace("%u", str(idx))
                if self.get_pad(pad_name) is not None:
                    raise ElementError(f"{self.describe()}: pad {pad_name} exists")
                return self._add_pad(tmpl, pad_name)
        raise ElementError(f"{self.describe()}: no request template for {direction.value}")

    def get_compatible_pad(self, direction: PadDirection) -> Pad:
        """First unlinked pad in ``direction``, creating a request pad if needed."""
        pads = self.sink_pads if direction is PadDirection.SINK else self.src_pads
        for p in pads:
            if not p.is_linked:
                return p
        return self.request_pad(direction)

    def link(self, downstream: "Element") -> "Element":
        src = self.get_compatible_pad(PadDirection.SRC)
        sink = downstream.get_compatible_pad(PadDirection.SINK)
        src.link(sink)
        return downstream

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Transition to running; override to allocate resources."""

    def stop(self) -> None:
        """Transition to stopped; override to release resources."""

    def reset_flow(self) -> None:
        """Reset per-run stream state so the pipeline can replay after a
        stop(): EOS latches and negotiated caps are cleared (caps are
        re-announced by sources on the next start). Override to clear
        element-specific accumulation; always call super()."""
        with self._lock:
            self._eos_sent = False
        self._negotiated = False
        # restart safety: a replay must never dispatch through a fused
        # callable planned for the PREVIOUS run (play() re-installs fresh
        # segments after this reset — see runtime/fusion.py)
        self._fusion_head = None
        self._fusion_member = None
        for pad in self.sink_pads + self.src_pads:
            pad.got_eos = False
            pad.caps = None

    # -- latency ------------------------------------------------------------
    def report_latency(self):
        """This element's contribution (seconds) to the pipeline LATENCY
        query, or None if it adds none / doesn't report (reference:
        GST_QUERY_LATENCY handling — elements add their processing latency
        as the query travels upstream, tensor_filter.c:1386-1418)."""
        return None

    # -- messages -----------------------------------------------------------
    def post_message(self, msg_type: MessageType, **data) -> None:
        if self.pipeline is not None:
            self.pipeline.bus.post(Message(msg_type, self.name, data))

    def post_error(self, error: str) -> None:
        logger.error("%s: %s", self.describe(), error)
        self.post_message(MessageType.ERROR, error=error)
        if self.pipeline is not None:
            self.pipeline._element_error(self, error)

    # -- data flow ----------------------------------------------------------
    def _chain_guarded(self, pad: Pad, buf: Buffer) -> None:
        if not self.props["silent"]:
            logger.info(
                "%s: buffer on %s pts=%s tensors=%d",
                self.describe(), pad.name, buf.pts,
                getattr(buf, "num_tensors", len(buf.tensors)))
        try:
            # fused-segment head: the whole device chain runs as ONE XLA
            # dispatch (runtime/fusion.py); a defused segment (untraceable
            # member) returns False and the normal per-element path runs
            seg = self._fusion_head
            if seg is not None and seg.dispatch(pad, buf):
                return
            self.chain(pad, buf)
        except Exception as e:  # noqa: BLE001 - becomes a pipeline ERROR message
            logger.debug("%s", traceback.format_exc())
            self.post_error(f"{type(e).__name__}: {e}")

    def chain(self, pad: Pad, buf: Buffer) -> None:
        raise NotImplementedError(f"{self.describe()} cannot receive buffers")

    def push(self, buf: Buffer, pad: Optional[Pad] = None) -> None:
        (pad or self.srcpad).push(buf)

    # -- events & negotiation ----------------------------------------------
    def _handle_sink_event_guarded(self, pad: Pad, event: Event) -> None:
        try:
            self.handle_sink_event(pad, event)
        except Exception as e:  # noqa: BLE001
            logger.debug("%s", traceback.format_exc())
            self.post_error(f"{type(e).__name__}: {e}")

    def handle_sink_event(self, pad: Pad, event: Event) -> None:
        if event.type is EventType.CAPS:
            caps: Caps = event.data["caps"]
            if not pad.template.caps.can_intersect(caps):
                raise ElementError(
                    f"caps {caps} not accepted on {pad.full_name} "
                    f"(template {pad.template.caps})"
                )
            pad.caps = caps
            self.set_caps(pad, caps)
            self.maybe_negotiate()
            # caps (re)negotiation reconfigures this element's transform:
            # a fused segment holding a callable traced against the OLD
            # caps must re-resolve on the next buffer
            seg = self._fusion_member
            if seg is not None:
                seg.invalidate()
        elif event.type is EventType.EOS:
            pad.got_eos = True
            if all(p.got_eos for p in self.sink_pads if p.is_linked):
                self.handle_eos()
        else:
            self.forward_event(event)

    def handle_eos(self) -> None:
        """All sink pads reached EOS. Default: flush + forward downstream."""
        self.send_eos()

    def send_eos(self) -> None:
        with self._lock:
            if self._eos_sent:
                return
            self._eos_sent = True
        for p in self.src_pads:
            p.push_event(Event.eos())

    def forward_event(self, event: Event) -> None:
        for p in self.src_pads:
            p.push_event(event)

    def handle_src_event(self, pad: Pad, event: Event) -> None:
        """Upstream event arriving on a src pad (e.g. QoS). Default: forward."""
        for p in self.sink_pads:
            p.send_upstream(event)

    # negotiation ------------------------------------------------------------
    def set_caps(self, pad: Pad, caps: Caps) -> None:
        """Input caps accepted; configure internal state. Override as needed."""

    def transform_caps(self, src_pad: Pad) -> Caps:
        """Compute this src pad's caps from negotiated sink caps.

        Default: passthrough of the first sink pad's caps (GstBaseTransform
        identity behavior). Called only when every linked sink pad has caps.
        """
        if self.sink_pads:
            return self.sink_pads[0].caps
        raise NotImplementedError(f"{self.describe()}: source must override transform_caps")

    def maybe_negotiate(self) -> None:
        """If all linked sink pads have caps, negotiate+announce src caps."""
        linked = [p for p in self.sink_pads if p.is_linked]
        if not linked or any(p.caps is None for p in linked):
            return
        self.negotiate_src()

    def negotiate_src(self) -> None:
        for pad in self.src_pads:
            if not pad.is_linked:
                continue
            out = self.transform_caps(pad)
            if out is None or out.is_empty:
                raise ElementError(f"{pad.full_name}: no output caps")
            peer_tmpl = pad.peer.template.caps
            out = out.intersect(peer_tmpl)
            if out.is_empty:
                raise ElementError(
                    f"{pad.full_name}: caps rejected by {pad.peer.full_name} "
                    f"(template {peer_tmpl})"
                )
            if not out.is_fixed:
                out = out.fixate()
            if pad.caps is not None and pad.caps == out:
                continue
            pad.push_event(Event.caps(out))
        self._negotiated = True

    def describe(self) -> str:
        return f"{self.ELEMENT_NAME or type(self).__name__}:{self.name}"

    def __repr__(self):
        return f"<{self.describe()}>"


class TransformElement(Element):
    """1-sink/1-src element transforming each buffer (GstBaseTransform)."""

    def chain(self, pad: Pad, buf: Buffer) -> None:
        out = self.transform(buf)
        if out is None:
            return  # dropped
        self.push(out)

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        raise NotImplementedError


class SourceElement(Element):
    """Push source running its own task thread (GstBaseSrc + its task).

    Subclasses implement ``create() -> Buffer | None`` (None = EOS) and
    ``get_src_caps() -> Caps`` announced before the first buffer.
    """

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._thread: Optional[threading.Thread] = None
        self._running = threading.Event()

    def get_src_caps(self) -> Caps:
        raise NotImplementedError

    def create(self) -> Optional[Buffer]:
        raise NotImplementedError

    def start(self) -> None:
        if self._thread is not None:
            return
        self._running.set()
        self._thread = threading.Thread(target=self._task, name=f"src:{self.name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running.clear()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._running.is_set()

    def _task(self) -> None:
        try:
            caps = self.get_src_caps()
            if not caps.is_fixed:
                caps = caps.fixate()
            for pad in self.src_pads:
                if pad.is_linked:
                    pad.push_event(Event.caps(caps))
            while self._running.is_set():
                buf = self.create()
                if buf is None:
                    # EOS only on natural stream end; a stop() cancellation
                    # must not fake a clean completion on the bus.
                    if self._running.is_set():
                        self.send_eos()
                    return
                self.push(buf)
        except Exception as e:  # noqa: BLE001
            logger.debug("%s", traceback.format_exc())
            self.post_error(f"{type(e).__name__}: {e}")


class SinkElement(Element):
    """Terminal element (GstBaseSink): renders buffers, reports EOS."""

    def chain(self, pad: Pad, buf: Buffer) -> None:
        self.render(buf)
        # rendered-buffer progress: the service watchdog's liveness signal
        # (counted only AFTER a successful render, so a crashing sink
        # never reads as progress)
        if self.pipeline is not None:
            self.pipeline.sink_buffer_count += 1

    def render(self, buf: Buffer) -> None:
        raise NotImplementedError

    def handle_eos(self) -> None:
        if self.pipeline is not None:
            self.pipeline._sink_reached_eos(self)
