"""Queue element: the thread boundary + backpressure primitive (L0').

Reference analog: GStreamer's ``queue`` element — the *only* source of
pipeline-stage parallelism in the reference (SURVEY.md §3.2: "parallelism
comes only from queue elements between filters"). A bounded buffer decouples
the upstream thread from a dedicated downstream worker; a full queue blocks
the producer (backpressure) or drops buffers when ``leaky``.

Only buffers count against ``max-size-buffers``; serialized events (CAPS/EOS)
are never dropped, never reordered, and never block.
"""
from __future__ import annotations

import threading
from collections import deque
from time import monotonic as _monotonic
from typing import Optional

from ..analysis import sanitizer as _san
from ..analysis.sanitizer import named_condition
from ..core import Buffer, Caps, Event, EventType
from ..obs import profile as obs_profile
from ..core.caps import any_media_caps
from ..runtime.element import Element, Prop
from .pad import Pad, PadDirection, PadTemplate


_STOP = ("stop", None)


class _Channel:
    """Bounded MPSC channel: buffers obey capacity/leaky policy, events pass
    through in order unconditionally."""

    def __init__(self, capacity: int, leaky: str, name: str = "?"):
        self.capacity = capacity  # 0 = unbounded
        self.leaky = leaky
        # per-instance lock name: chained queues nest naturally (worker of
        # one pushes into the next) and must stay distinct graph nodes
        self._cond = named_condition(f"queue[{name}]._cond")
        self._dq: deque = deque()   # guarded-by: _cond
        self._closed = False        # guarded-by: _cond
        # buffers in _dq (events excluded), O(1) hot path
        self._n_bufs = 0            # guarded-by: _cond
        # leaky-mode loss accounting: upstream = incoming buffer refused,
        # downstream = oldest queued buffer evicted. Silent drops make
        # buffer loss invisible to the service health snapshot.
        self.dropped_upstream = 0    # guarded-by: _cond
        self.dropped_downstream = 0  # guarded-by: _cond
        # plan-time depth retunes (runtime/placement.py)
        self.retuned = 0             # guarded-by: _cond

    def reset_counters(self) -> None:
        with self._cond:
            self.dropped_upstream = 0
            self.dropped_downstream = 0

    def set_capacity(self, capacity: int) -> None:
        """Retune the depth at plan time (placement-planner hook). Safe
        against the producer/worker paths: capacity is only read under
        ``_cond``, and blocked producers are woken so a RAISED capacity
        (or a switch to unbounded) admits them immediately instead of on
        the next bounded wait slice / worker pop."""
        capacity = max(0, int(capacity))
        with self._cond:
            if capacity == self.capacity:
                return
            self.capacity = capacity
            self.retuned += 1
            self._cond.notify_all()

    def put_buf(self, buf: Buffer) -> None:
        with self._cond:
            if self.capacity > 0 and self._n_bufs >= self.capacity:
                if self.leaky == "upstream":
                    self.dropped_upstream += 1
                    return  # drop the incoming (newest) buffer
                if self.leaky == "downstream":
                    for i, (kind, _) in enumerate(self._dq):
                        if kind == "buf":
                            del self._dq[i]  # drop the oldest buffer
                            self._n_bufs -= 1
                            self.dropped_downstream += 1
                            break
                else:
                    # re-read capacity every iteration: a concurrent
                    # set_capacity may raise it (wake via its notify) or
                    # set it to 0 = unbounded — a stale bound here would
                    # park this producer against a limit that no longer
                    # exists (it could only ever leave via the worker
                    # pop's notify, racing the retune)
                    while (not self._closed and self.capacity > 0
                           and self._n_bufs >= self.capacity):
                        self._cond.wait(0.25)  # backpressure, bounded slice
                    if self._closed:
                        return
            self._dq.append(("buf", buf))
            self._n_bufs += 1
            self._cond.notify_all()

    def put_event(self, event: Event) -> None:
        with self._cond:
            self._dq.append(("event", event))
            self._cond.notify_all()

    def put_stop(self) -> None:
        with self._cond:
            self._closed = True
            self._dq.append(_STOP)
            self._cond.notify_all()

    def get(self):
        with self._cond:
            while not self._dq:
                # bounded slice: the stop sentinel normally wakes this,
                # but a worker must never be parked unwakeably forever
                self._cond.wait(0.25)
            item = self._dq.popleft()
            if item[0] == "buf":
                self._n_bufs -= 1
            self._cond.notify_all()
            return item

    def clear(self) -> None:
        with self._cond:
            self._dq.clear()
            self._n_bufs = 0
            self._cond.notify_all()

    def reopen(self) -> None:
        with self._cond:
            self._closed = False


class QueueElement(Element):
    ELEMENT_NAME = "queue"
    # fusion barrier (runtime/fusion.py): the queue IS the thread +
    # backpressure boundary — fusing across it would delete the
    # pipeline-stage parallelism it exists to provide
    FUSION_BARRIER = "queue boundary (thread + backpressure decoupling)"
    SINK_TEMPLATES = (PadTemplate("sink", PadDirection.SINK, any_media_caps()),)
    SRC_TEMPLATES = (PadTemplate("src", PadDirection.SRC, any_media_caps()),)
    PROPERTIES = {
        "max_size_buffers": Prop(16, int, "queue capacity in buffers (0 = unbounded)"),
        "leaky": Prop("no", str, "no | upstream (drop new) | downstream (drop old)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._ch = _Channel(self.props["max_size_buffers"],
                            self.props["leaky"], name=self.name)
        self._thread: Optional[threading.Thread] = None
        self._running = threading.Event()

    @property
    def stats(self) -> dict:
        """Loss/occupancy counters (picked up by Pipeline.element_stats and
        the service health snapshot): leaky drops are counted, not silent."""
        ch = self._ch
        return {
            "level": ch._n_bufs,
            "capacity": ch.capacity,
            "leaky": ch.leaky,
            "dropped_upstream": ch.dropped_upstream,
            "dropped_downstream": ch.dropped_downstream,
            "retuned": ch.retuned,
        }

    def set_capacity(self, capacity: int) -> None:
        """Planner-tuned depth (runtime/placement.py): resize the bounded
        channel without stopping flow; counted in ``stats['retuned']``."""
        self._ch.set_capacity(capacity)

    def reset_flow(self) -> None:
        super().reset_flow()
        self._ch.reset_counters()

    # -- producer side ------------------------------------------------------
    def chain(self, pad: Pad, buf: Buffer) -> None:
        if obs_profile.ACTIVE:
            # queue-wait attribution: stamp entry, measured at the worker
            # pop (one module-global check when profiling is off; the
            # meta stamp races benignly on tee-shared buffers, same
            # contract as InterLatencyTracer's birth stamp)
            buf.meta["_prof_q_t0"] = _monotonic()
        self._ch.put_buf(buf)

    def handle_sink_event(self, pad: Pad, event: Event) -> None:
        if event.type is EventType.CAPS:
            pad.caps = event.data["caps"]
            self._ch.put_event(event)
        elif event.type is EventType.EOS:
            pad.got_eos = True
            self._ch.put_event(event)
        elif event.type is EventType.FLUSH:
            self._ch.clear()
            self.forward_event(event)
        else:
            self._ch.put_event(event)

    # -- consumer side ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._ch.reopen()
        self._running.set()
        self._thread = threading.Thread(target=self._task, name=f"queue:{self.name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running.clear()
        self._ch.put_stop()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None
        self._ch.clear()

    def _task(self) -> None:
        while self._running.is_set():
            kind, payload = self._ch.get()
            if kind == "stop":
                return
            if kind == "buf":
                # pop unconditionally: a stamp from a profiling session
                # that ended while the buffer was queued must not ride
                # the meta downstream (and onto the query wire) forever
                t0 = payload.meta.pop("_prof_q_t0", None)
                if t0 is not None and obs_profile.ACTIVE:
                    obs_profile.record_queue_wait(
                        obs_profile.series_name(self),
                        _monotonic() - t0, self._ch._n_bufs)
                if _san.XFER:
                    # queue hand-off choke point: byte-accounting only —
                    # a disallow scope here would outlaw the legitimate
                    # host elements running on this worker thread. Device
                    # buffers cross by reference (zero copy), and the
                    # ledger proves it: "queue" rows carry bytes moved,
                    # not bytes copied.
                    _san.note_transfer(
                        f"queue:{self.name}",
                        "device" if payload.on_device else "host",
                        payload.nbytes)
                try:
                    self.srcpad.push(payload)
                except Exception as e:  # noqa: BLE001
                    self.post_error(f"{type(e).__name__}: {e}")
            elif payload.type is EventType.EOS:
                self.send_eos()
                return
            else:
                self.forward_event(payload)
