"""Pipeline ↔ pbtxt (MediaPipe-style graph text) conversion.

Reference analog: ``tools/development/parser/convert.c`` — the
reference's gst-pipeline↔pbtxt converter for its visual pipeline editor.
Same emitted shape, faithfully:

  * top-level ``input_stream:`` / ``output_stream:`` lines for elements
    with no sink pads (sources) / no src pads (sinks);
  * one ``node { calculator: "<element>Calculator" ... }`` block per
    element that has BOTH sides, its streams named by the producing pad:
    ``<element>_<node_index>_<pad_index>`` (sources contribute their node
    name directly — "any src has only one pad", convert.c:53-60);
  * node naming: first instance of an element type keeps the bare
    element name, later ones get ``_<index+1>`` (convert.c:28-39);
  * a stream feeding a SINK is named after the sink's node name
    (convert.c pbtxt_print_node_output_stream:79-81 — "assume that any
    sink has only one pad"), so the top-level ``output_stream`` line
    references a stream some node actually produces;
  * properties ARE carried, in ``node_options`` (the reference left this
    as a TODO, convert.c:111): each non-default scalar property becomes
    an ``option: "key=value"`` entry. Topology-only consumers can ignore
    the block; ``from_pbtxt`` replays the options into the launch line.

``from_pbtxt`` rebuilds a launch string from that topology: producers
are resolved by stream name, fan-out becomes a named ``tee``-style
segment reference (``name=X`` + ``X.`` chains), multi-input nodes use
the launch grammar's pad-reference form. Sinks resolve by stream NAME
(conformant emissions); files from other tools that name sink streams
differently fall back to in-order attachment to dangling streams.
"""
from __future__ import annotations

import re
from typing import Dict, List, Tuple

_OPTIONS_TYPE = "type.googleapis.com/nnstreamer.LaunchOptions"


def _kind(el) -> str:
    return el.ELEMENT_NAME or type(el).__name__.lower()


def _number_elements(pipeline):
    """One pass: element runtime-name -> (per-kind index, pbtxt node
    name per the reference numbering — bare kind for the first instance,
    ``kind_<i+1>`` after)."""
    seen: Dict[str, int] = {}
    indices: Dict[str, int] = {}
    names: Dict[str, str] = {}
    for el in pipeline.elements.values():
        kind = _kind(el)
        i = seen.get(kind, 0)
        seen[kind] = i + 1
        indices[el.name] = i
        names[el.name] = kind if i == 0 else f"{kind}_{i + 1}"
    return indices, names


def _launch_options(el) -> List[str]:
    """Non-default scalar properties as launch-spelling ``key=value``
    strings (dashes, booleans as true/false). Properties holding parsed
    non-scalar values (e.g. combination tuples) are emitted from their
    original launch value when the element kept one, else skipped —
    pbtxt remains loadable either way."""
    out: List[str] = []
    # property tables are split across the MRO (Element merges them in
    # __init__ as _prop_defs) — reading one class's table would omit
    # inherited props like a paced source's num-buffers
    declared = getattr(el, "_prop_defs", None) or getattr(
        type(el), "PROPERTIES", {})
    values = getattr(el, "props", {})
    for key, prop in declared.items():
        v = values.get(key, prop.default)
        if v == prop.default or v is None:
            continue
        if isinstance(v, bool):
            v = "true" if v else "false"
        elif not isinstance(v, (str, int, float)):
            continue
        v = str(v)
        if '"' in v:
            # no escaping scheme survives both the pbtxt string literal
            # and the launch grammar — skip rather than corrupt the value
            continue
        if any(c in v for c in " \t!"):
            v = '\\"' + v + '\\"'
        out.append(f"{key.replace('_', '-')}={v}")
    return out


def to_pbtxt(pipeline) -> str:
    """Emit the reference converter's pbtxt for a built Pipeline."""
    indices, names = _number_elements(pipeline)
    lines: List[str] = []

    def stream_of(src_pad) -> str:
        owner = src_pad.element
        if not getattr(owner, "sink_pads", ()):  # source: node name IS the stream
            return names[owner.name]
        peer = src_pad.peer
        if peer is not None and not getattr(peer.element, "src_pads", ()):
            # stream into a sink is named after the sink node
            # (convert.c:79-81) so the top-level output_stream line
            # references a produced stream
            return names[peer.element.name]
        pad_idx = list(owner.src_pads).index(src_pad)
        return f"{_kind(owner)}_{indices[owner.name]}_{pad_idx}"

    for el in pipeline.elements.values():
        if not getattr(el, "sink_pads", ()):
            lines.append(f'input_stream: "{names[el.name]}"')
        if not getattr(el, "src_pads", ()):
            lines.append(f'output_stream: "{names[el.name]}"')

    for el in pipeline.elements.values():
        sinks = getattr(el, "sink_pads", ())
        srcs = getattr(el, "src_pads", ())
        if not sinks or not srcs:
            continue
        kind = _kind(el)
        lines.append("")
        lines.append("node: {")
        lines.append(f'\tcalculator: "{kind}Calculator"')
        for pad in sinks:
            if pad.peer is not None:
                lines.append(f'\tinput_stream: "{stream_of(pad.peer)}"')
        for pad in srcs:
            lines.append(f'\toutput_stream: "{stream_of(pad)}"')
        opts = _launch_options(el)
        if opts:
            lines.append("\tnode_options: {")
            lines.append(f"\t\t[{_OPTIONS_TYPE}] {{")
            for o in opts:
                lines.append(f'\t\t\toption: "{o}"')
            lines.append("\t\t}")
            lines.append("\t}")
        lines.append("}")
    return "\n".join(lines) + "\n"


_NODE_HEAD_RE = re.compile(r"node:?\s*\{")
_FIELD_RE = re.compile(r'(calculator|input_stream|output_stream):\s*"([^"]+)"')
_OPTION_RE = re.compile(r'option:\s*"((?:[^"\\]|\\.)*)"')
_SRC_INDEX_RE = re.compile(r"_\d+$")


def _split_nodes(text: str) -> Tuple[str, List[str]]:
    """(top-level text, node bodies) with BALANCED brace scanning — the
    protobuf text format allows both ``node {`` and ``node: {`` heads
    and nested sub-blocks (node_options) inside a node."""
    bodies: List[str] = []
    top_parts: List[str] = []
    pos = 0
    while True:
        m = _NODE_HEAD_RE.search(text, pos)
        if m is None:
            top_parts.append(text[pos:])
            return "".join(top_parts), bodies
        top_parts.append(text[pos:m.start()])
        depth = 1
        i = m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        if depth:
            raise ValueError("pbtxt: unbalanced braces in node block")
        bodies.append(text[m.end():i - 1])
        pos = i


def from_pbtxt(text: str) -> str:
    """Rebuild a launch string from pbtxt topology.

    Properties don't round-trip (the format doesn't carry them — same
    limitation as the reference converter). Sink attachment is a
    documented HEURISTIC: the format records sinks only as top-level
    ``output_stream`` names with no producer link, so each listed sink
    is attached to the next dangling (consumer-less) node stream in
    order — correct for every pipeline the emitter produces, ambiguous
    only for hand-written pbtxt with reordered sink lines.
    """
    top_text, node_bodies = _split_nodes(text)
    top_inputs: List[str] = []
    top_outputs: List[str] = []
    nodes: List[Tuple[str, List[str], List[str], List[str]]] = []
    for body in node_bodies:
        fields = _FIELD_RE.findall(body)
        calc = [v for k, v in fields if k == "calculator"]
        ins = [v for k, v in fields if k == "input_stream"]
        outs = [v for k, v in fields if k == "output_stream"]
        opts = [o.replace('\\"', '"') for o in _OPTION_RE.findall(body)]
        if not calc:
            raise ValueError("pbtxt node without calculator")
        el = calc[0]
        if el.endswith("Calculator"):
            el = el[: -len("Calculator")]
        nodes.append((el, ins, outs, opts))
    for m in _FIELD_RE.finditer(top_text):
        if m.group(1) == "input_stream":
            top_inputs.append(m.group(2))
        elif m.group(1) == "output_stream":
            top_outputs.append(m.group(2))

    # producer stream name -> launch name of the producing element
    produced: Dict[str, str] = {}
    counts: Dict[str, int] = {}

    def fresh(kind: str) -> str:
        counts[kind] = counts.get(kind, 0) + 1
        return f"{kind}_n{counts[kind]}"

    src_kinds: Dict[str, str] = {}
    for s in top_inputs:
        kind = _SRC_INDEX_RE.sub("", s)  # source node name = element[_i]
        src_kinds[s] = kind
        produced[s] = fresh(kind)
    for el, ins, outs, _opts in nodes:
        name = fresh(el)
        for o in outs:
            produced[o] = name

    # emit: each top-level source opens a segment; nodes chain from their
    # first input's producer, additional inputs use pad references
    segs: List[str] = []
    consumed: set = set()
    for s in top_inputs:
        segs.append(f"{src_kinds[s]} name={produced[s]}")
    for el, ins, outs, opts in nodes:
        name = produced[outs[0]] if outs else fresh(el)
        head = " ".join([el, f"name={name}", *opts])
        first = True
        for i in ins:
            if i not in produced:
                raise ValueError(f"pbtxt stream '{i}' has no producer")
            consumed.add(i)
            src = produced[i]
            if first:
                segs.append(f"{src}. ! {head}")
                first = False
            else:
                segs.append(f"{src}. ! {name}.")
        if not ins:
            segs.append(head)
    # sinks: a conformant emission names the stream feeding a sink after
    # the sink node (convert.c:79-81), so resolve by NAME first; foreign
    # files that didn't fall back to in-order attachment to the
    # remaining dangling (consumer-less) streams
    dangling = [s for s in produced if s not in consumed]
    leftover_outputs: List[str] = []
    for sink_stream in top_outputs:
        if sink_stream in produced and sink_stream in dangling:
            dangling.remove(sink_stream)
            kind = _SRC_INDEX_RE.sub("", sink_stream)
            segs.append(
                f"{produced[sink_stream]}. ! {kind} name={fresh(kind)}")
        else:
            leftover_outputs.append(sink_stream)
    for sink_stream, feed in zip(leftover_outputs, dangling):
        kind = _SRC_INDEX_RE.sub("", sink_stream)
        segs.append(f"{produced[feed]}. ! {kind} name={fresh(kind)}")
    return "  ".join(segs)


def main() -> None:  # pragma: no cover - CLI helper, exercised via __main__
    import sys

    from .parse import parse_launch

    print(to_pbtxt(parse_launch(sys.argv[1])))


if __name__ == "__main__":  # pragma: no cover
    main()
