"""Pipeline container, state machine, and message bus (L0' substrate).

Reference analog: GstPipeline + GstBus. States collapse to the useful subset
(NULL/PLAYING — the reference's READY/PAUSED exist to stage caps negotiation,
which in our design is event-driven and needs no separate state).
"""
from __future__ import annotations

import os
import queue as _queue
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Set

from ..analysis.sanitizer import named_lock, named_rlock
from ..core import Message, MessageType
from ..obs import flight as obs_flight
from ..utils.log import logger
from ..utils.threads import ThreadRegistry
from .element import Element, SinkElement, SourceElement


class Bus:
    """Thread-safe out-of-band message stream from elements to the app."""

    def __init__(self):
        self._q: _queue.Queue = _queue.Queue()

    def post(self, msg: Message) -> None:
        self._q.put(msg)

    def pop(self, timeout: Optional[float] = None) -> Optional[Message]:
        try:
            return self._q.get(timeout=timeout)
        except _queue.Empty:
            return None

    def wait_for(self, types: Iterable[MessageType], timeout: float = 10.0) -> Optional[Message]:
        """Block until a message of one of ``types`` arrives (or timeout)."""
        types = set(types)
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            msg = self.pop(timeout=remaining)
            if msg is not None and msg.type in types:
                return msg


class Pipeline:
    """A runnable graph of elements."""

    def __init__(self, name: str = "pipeline", validate: bool = False,
                 fuse: Optional[bool] = None, place=None):
        self.name = name
        # opt-in static validation at play(): the graph linter
        # (analysis.lint_pipeline) runs before data flows and logs its
        # findings as warnings — runtime and static checks share one
        # diagnostic path, but validation never blocks a play() the
        # caller asked for (warn-only; use the lint CLI to gate hard)
        self.validate = validate
        # device-segment fusion (runtime/fusion.py): ON by default — each
        # linear run of device elements becomes one XLA dispatch per
        # buffer. fuse=False (or the NNS_NO_FUSE=1 escape hatch) keeps
        # the classic per-element dispatch path.
        if fuse is None:
            fuse = os.environ.get("NNS_NO_FUSE", "") not in ("1", "true", "yes")
        self.fuse = bool(fuse)
        self._fused_segments: list = []  # set by fusion.install at play()
        # profile-guided cross-device placement (runtime/placement.py):
        # OFF by default — place="auto" plans fused segments across the
        # local device farm from the ProfileStore (calibrating on a
        # miss) and tunes inter-stage queue depths; a PlacementPlan
        # instance applies a serialized plan verbatim. NNS_NO_PLACE=1 is
        # the operational kill switch (wins over any constructor value).
        if os.environ.get("NNS_NO_PLACE", "") in ("1", "true", "yes"):
            place = None
        self.place = place
        self._placement_state = None  # set by placement.install at play()
        self.elements: Dict[str, Element] = {}
        self.bus = Bus()
        # running-time anchor, set at each play() (GStreamer base_time analog)
        self.play_t0_mono: Optional[float] = None
        self._playing = False
        self._lock = named_lock("Pipeline._lock")
        self._eos_sinks: Set[str] = set()  # guarded-by: _lock
        # serializes play()/stop()/error-halt so a stale halt (spawned
        # for a run that a supervised restart has since replaced) can
        # never stop the NEW run's sources. Element threads must never
        # take this lock (play/stop join them while holding it) — the
        # error path only READS the epoch and spawns, it does not block.
        self._state_lock = named_rlock("Pipeline._state_lock")
        self._play_epoch = 0  # guarded-by: _state_lock
        self._halt_threads = ThreadRegistry()
        # -- control-plane hooks (service layer) -----------------------------
        # buffers rendered at ANY sink since the last play(); the service
        # health watchdog reads this as "is data still making it through"
        # (a plain int: += under the GIL is close enough for a watchdog,
        # and the render path must stay lock-free)
        self.sink_buffer_count = 0
        # out-of-band state listeners: cb(kind, source, data) with kind in
        # {"playing", "stopped", "eos", "error"}. Unlike the Bus (a queue
        # one consumer drains), listeners fan out — the supervisor can
        # watch a pipeline whose bus the application owns.
        self._state_listeners: List[Callable[[str, str, dict], None]] = []

    # -- construction -------------------------------------------------------
    def add(self, *elements: Element) -> "Pipeline":
        for el in elements:
            if el.name in self.elements:
                raise ValueError(f"duplicate element name '{el.name}'")
            self.elements[el.name] = el
            el.pipeline = self
        return self

    def get(self, name: str) -> Element:
        return self.elements[name]

    def link(self, *chain: Element) -> None:
        for up, down in zip(chain, chain[1:]):
            up.link(down)

    def add_state_listener(self, cb: Callable[[str, str, dict], None]) -> None:
        """Subscribe to out-of-band lifecycle notifications (see __init__).
        Listeners run on the notifying thread and must not block."""
        self._state_listeners.append(cb)

    def remove_state_listener(self, cb) -> None:
        if cb in self._state_listeners:
            self._state_listeners.remove(cb)

    def _notify_state(self, kind: str, source: str, data: dict) -> None:
        # every lifecycle transition lands in the always-on flight
        # recorder — the postmortem tail a CrashReport embeds
        obs_flight.record(
            "pipeline", kind,
            {"source": source,
             **({"error": str(data.get("error"))[:200]}
                if kind == "error" else {})},
            pipeline=self.name)
        for cb in list(self._state_listeners):
            try:
                cb(kind, source, data)
            except Exception:  # noqa: BLE001 - a listener must not kill flow
                logger.exception("state listener failed for %s", kind)

    def element_stats(self) -> Dict[str, dict]:
        """Per-element runtime counters for every element exposing a
        ``.stats`` dict (queues: drop/level counters; tensor_fault:
        injection counters). The service health snapshot surfaces this."""
        out: Dict[str, dict] = {}
        for el in self.elements.values():
            stats = getattr(el, "stats", None)
            if isinstance(stats, dict) and stats:
                out[el.name] = dict(stats)
            elif hasattr(stats, "snapshot"):  # InvokeStats (tensor_filter)
                out[el.name] = stats.snapshot()
        # fused device segments report as pseudo-elements so the service
        # health snapshot sees one-dispatch chains (docs/observability.md)
        for seg in self._fused_segments:
            if seg.stats.get("dispatches") or seg.stats.get("defused"):
                out[f"fused:{seg.name}"] = dict(seg.stats)
        return out

    @property
    def fused_segments(self) -> list:
        """The FusedSegments installed by the last play() (empty when
        fuse=False or nothing fused)."""
        return list(self._fused_segments)

    @property
    def placement_plan(self):
        """The PlacementPlan applied by the last play() (None when
        placement is off or nothing planned)."""
        state = self._placement_state
        return state.plan if state is not None else None

    @property
    def sinks(self) -> List[SinkElement]:
        return [e for e in self.elements.values() if isinstance(e, SinkElement)]

    @property
    def sources(self) -> List[SourceElement]:
        return [e for e in self.elements.values() if isinstance(e, SourceElement)]

    # -- state --------------------------------------------------------------
    def play(self) -> "Pipeline":
        with self._state_lock:
            if self._playing:
                return self
            from ..utils import trace

            trace.install_from_env()   # NNS_TRACERS (GST_TRACERS analog)
            trace.dump_dot(self)       # NNS_DOT_DIR (GST_DEBUG_DUMP_DOT_DIR)
            if self.validate:
                self._run_static_validation()
            self._validate_links()
            self._playing = True
            self._play_epoch += 1
            self.play_t0_mono = time.monotonic()
            self.sink_buffer_count = 0
            with self._lock:
                self._eos_sinks.clear()
            for el in self.elements.values():
                el.reset_flow()
            # plan fused device segments AFTER flow reset (a restart must
            # never reuse the previous run's callables) and BEFORE
            # elements start; the composed jit resolves lazily once caps
            # have negotiated — see runtime/fusion.py
            from . import fusion

            if self.fuse:
                fusion.install(self)
            else:
                fusion.uninstall(self)
            # placement AFTER fusion: the planner assigns the freshly
            # installed segments (and re-plans from scratch on every
            # play, so a supervised restart never keeps a stale
            # assignment — same contract as the fusion cache)
            if self.place:
                from . import placement

                placement.install(self)
            elif self._placement_state is not None:
                from . import placement

                placement.uninstall(self)
            # memory accounting (obs/memory.py): queue-occupancy bytes
            # are read off live pipelines at scrape time
            from ..obs import memory as obs_memory

            obs_memory.track_pipeline(self)
            # start non-sources first so queues/filters are ready before
            # data flows
            for el in self.elements.values():
                if not isinstance(el, SourceElement):
                    el.start()
            for el in self.sources:
                el.start()
        # notify OUTSIDE the state lock: listeners (the service layer)
        # take their own locks, and holding ours across them would order
        # Pipeline._state_lock -> Service._lock against the start() path
        self.bus.post(Message(MessageType.STATE_CHANGED, self.name, {"state": "playing"}))
        self._notify_state("playing", self.name, {})
        return self

    def stop(self) -> "Pipeline":
        with self._state_lock:
            if not self._playing:
                return self
            self._playing = False
            for el in self.sources:
                el.stop()
            for el in self.elements.values():
                if not isinstance(el, SourceElement):
                    el.stop()
        # joined outside _state_lock — the halt threads acquire it
        self._halt_threads.drain(timeout_per=2.0)
        # explicit metrics unregister sweep: a stopped pipeline's
        # nns_fused_* / nns_placement_* / queue-bytes rows must leave the
        # scrape NOW, not whenever GC collects the weak refs (a replay
        # re-tracks at play())
        from ..obs import memory as obs_memory
        from ..obs import metrics as obs_metrics

        obs_metrics.untrack_pipeline(self)
        obs_memory.untrack_pipeline(self)
        if self._placement_state is not None:
            # an open calibration window must not outlive the run that
            # was feeding it samples (recording refcount balance)
            from . import placement

            placement.on_stop(self)
        from ..utils import trace

        if trace.ACTIVE:
            # env-activated chrome traces flush at every stop(), not only
            # at interpreter exit — a long-lived serve process produces
            # inspectable traces per run
            trace.flush_chrome_traces()
        self.bus.post(Message(MessageType.STATE_CHANGED, self.name, {"state": "stopped"}))
        self._notify_state("stopped", self.name, {})
        return self

    @property
    def playing(self) -> bool:
        return self._playing

    # -- LATENCY query -------------------------------------------------------
    def query_latency(self) -> dict:
        """Pipeline-wide latency answer (reference GST_QUERY_LATENCY as
        driven by tensor_filter's latency-report,
        tensor_filter.c:1386-1418): the query conceptually travels from
        each sink upstream, every element adding its ``report_latency()``
        contribution (tensor_filter pads its estimate with 5% headroom and
        remembers what it reported, so LATENCY bus messages only fire when
        the estimate escapes that headroom). Returns::

            {"latency_s": worst sink-to-source path total,
             "per_element": {name: contribution_s},   # reporting elements
             "per_sink": {sink_name: path_total_s}}
        """
        per_element: Dict[str, float] = {}
        memo: Dict[str, float] = {}

        def upstream(el: Element, on_path: frozenset) -> float:
            if el.name in memo:
                return memo[el.name]
            if el.name in on_path:
                return 0.0  # feedback loop (tensor_repo): cut the cycle
            own = el.report_latency()
            if own is not None:
                per_element[el.name] = own
            branches = [
                upstream(pad.peer.element, on_path | {el.name})
                for pad in el.sink_pads
                if pad.peer is not None and pad.peer.element is not None
            ]
            total = (own or 0.0) + (max(branches) if branches else 0.0)
            memo[el.name] = total
            return total

        per_sink = {s.name: upstream(s, frozenset()) for s in self.sinks}
        return {
            "latency_s": max(per_sink.values()) if per_sink else 0.0,
            "per_element": per_element,
            "per_sink": per_sink,
        }

    def _run_static_validation(self) -> None:
        """Warn-only graph lint at play() (validate=True): every finding
        becomes a log warning, never an exception — see docs/lint.md."""
        from ..analysis import Severity, lint_pipeline

        try:
            diags = lint_pipeline(self)
        except Exception:  # noqa: BLE001 - validation must not block play
            logger.exception("%s: static validation failed to run", self.name)
            return
        for d in diags:
            # info findings (NNL013 fusion plans) are reports, not hazards
            log = (logger.info if d.severity is Severity.INFO
                   else logger.warning)
            log("%s: %s", self.name, d.format())

    def _validate_links(self) -> None:
        for el in self.elements.values():
            for pad in el.sink_pads:
                if not pad.is_linked:
                    logger.warning("%s: unlinked sink pad %s", self.name, pad.full_name)

    # -- EOS / error flow ----------------------------------------------------
    def _element_error(self, element: Element, error: str = "") -> None:
        """Fatal element error: halt sources so the graph drains instead of
        spinning (GStreamer: apps stop the pipeline on a bus ERROR; we stop
        producing immediately, the app still owns final stop())."""
        if not self._playing:
            return
        # epoch-stamped + tracked (joined by stop()), not fire-and-forget.
        # The stamp closes a TOCTOU race: this thread can be descheduled
        # between the _playing check and the halt running, a supervised
        # restart replaces the run meanwhile, and an unstamped halt would
        # then silently stop the NEW run's sources (no EOS, no error —
        # the service parks READY forever).
        t = threading.Thread(
            target=self._halt_sources, args=(self._play_epoch,),
            daemon=True, name=f"{self.name}:error-halt")
        t.start()
        self._halt_threads.track(t)
        self._notify_state("error", element.name,
                           {"element": element.name, "error": error})

    def _halt_sources(self, epoch: int) -> None:
        with self._state_lock:
            if epoch != self._play_epoch or not self._playing:
                return  # a restart replaced the run this halt belongs to
            for el in self.sources:
                try:
                    el.stop()
                except Exception:  # noqa: BLE001 - best-effort halt
                    logger.exception("error stopping %s", el.name)

    def _sink_reached_eos(self, sink: Element) -> None:
        with self._lock:
            self._eos_sinks.add(sink.name)
            done = len(self._eos_sinks) >= len(self.sinks)
        if done:
            self.bus.post(Message(MessageType.EOS, self.name, {}))
            self._notify_state("eos", self.name, {})

    def wait(self, timeout: float = 30.0) -> Message:
        """Run until EOS or ERROR; returns the terminating message."""
        msg = self.bus.wait_for((MessageType.EOS, MessageType.ERROR), timeout=timeout)
        if msg is None:
            raise TimeoutError(f"pipeline '{self.name}' did not reach EOS in {timeout}s")
        return msg

    def run(self, timeout: float = 30.0) -> Message:
        """play() + wait() + stop() convenience; raises on ERROR."""
        self.play()
        try:
            msg = self.wait(timeout=timeout)
        finally:
            self.stop()
        if msg.type is MessageType.ERROR:
            raise RuntimeError(f"pipeline error from {msg.source}: {msg.data.get('error')}")
        return msg

    # -- introspection -------------------------------------------------------
    def to_dot(self) -> str:
        """Graphviz dump (reference: GST_DEBUG_DUMP_DOT_DIR pipeline graphs)."""
        lines = [f'digraph "{self.name}" {{', "  rankdir=LR;"]
        for el in self.elements.values():
            lines.append(f'  "{el.name}" [shape=box,label="{el.describe()}"];')
        for el in self.elements.values():
            for pad in el.src_pads:
                if pad.is_linked:
                    caps = str(pad.caps) if pad.caps else ""
                    lines.append(
                        f'  "{el.name}" -> "{pad.peer.element.name}" [label="{caps}"];'
                    )
        lines.append("}")
        return "\n".join(lines)
