"""Device-segment fusion compiler (L0' substrate).

Inline push semantics charge every element hop a Python pad-hop plus —
for device elements — its own ``jax.jit`` dispatch per buffer
(``runtime/pad.py`` / ``elements/transform.py``). The reference's
headline claim is low per-element overhead versus raw framework
invocation (arxiv 1901.04985), and the multi-TPU follow-up shows model
*segmentation* dominating inference time (arxiv 2503.01025); this module
closes our side of that gap structurally: at ``Pipeline.play()`` every
linear run of ``DEVICE_AFFINITY == "device"`` elements is partitioned
into a **fused segment**, the per-element transforms compose into ONE
jitted callable, and a buffer entering the segment head costs a single
XLA dispatch instead of N chained chain()+dispatch hops.

Planning vs tracing: the segment *plan* is pure topology (pad shapes,
affinity, the ``Element.FUSABLE`` contract) and runs before PLAYING; the
composed callable is resolved lazily on the first buffer — after caps
negotiation has configured every member (``set_caps`` built the stage
functions) — and is cached until invalidated.

Segments break (a **fusion barrier**) at:
  * host/neutral-affinity elements (decoders, converters, queues, tees);
  * queue boundaries (thread + backpressure decoupling);
  * tee/demux fan-out and mux fan-in (any element without exactly one
    linked sink and one linked src pad, which also covers request pads
    in use);
  * ``tensor_if`` dynamic routing (per-buffer branch decision);
  * stateful elements opting out via ``Element.FUSABLE = False``
    (e.g. ``tensor_serving``: cross-buffer batching state);
  * per-instance disqualifiers reported by ``Element.fusion_barrier()``
    (e.g. ``tensor_filter invoke-dynamic`` / ``suspend`` / profiling).

Cache invalidation: a CAPS event reaching any member invalidates its
segment (re-traced on the next buffer), as do ``tensor_filter`` hot model
swaps (``commit_model`` / ``reload_model`` — the service control plane's
canary/swap path) and ``reset_flow()`` on restart (``Pipeline.play()``
re-plans from scratch, so a supervised restart never sees a stale fused
callable). Escape hatches: ``Pipeline(fuse=False)`` or ``NNS_NO_FUSE=1``.

See docs/fusion.md for the segmentation rules and barrier table.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Dict, List, Optional

from ..analysis import sanitizer as _san
from ..analysis.sanitizer import named_lock
from ..core import Buffer, clock_now
from ..obs import context as obs_context
from ..obs import memory as obs_memory
from ..obs import metrics as obs_metrics
from ..obs import profile as obs_profile
from ..obs import quality as obs_quality
from ..utils import trace
from ..utils.log import logger
from .element import Element

if TYPE_CHECKING:
    from .pipeline import Pipeline


# donation safety is TRANSITIVE: jit can alias an "output" back to an
# input array whenever the traced computation passes a tensor through
# unmodified (identity models, typecast to the same dtype, apply= skips,
# output-combination i<N> passthrough), so an array entering the segment
# may really be owned arbitrarily far upstream. Donation is therefore
# allowed only when EVERY transitive upstream element is in this
# allowlist (fresh per-frame producers and pure single-consumer movers)
# and has a single linked src pad — anything that shares (tee/demux),
# retains (aggregator/repo), duplicates (fault/rate), or lets the
# application keep a reference (appsrc-style injection) disqualifies.
_DONATION_SAFE_CHAIN = ("tensor_src", "capsfilter", "queue",
                        "tensor_transform", "tensor_filter")


def barrier_reason(el: "Element") -> Optional[str]:
    """Why ``el`` cannot join a fused segment (None = fusable candidate).

    Combines the element's own contract (``fusion_barrier()``: affinity,
    FUSABLE flag, per-instance disqualifiers) with the structural
    requirement of a linear chain: exactly one linked sink pad and one
    linked src pad (tee/mux/demux fan and in-use request pads all fail
    this). The graph linter's NNL010/NNL013 rules report these reasons.
    """
    reason = el.fusion_barrier()
    if reason is not None:
        return reason
    linked_sinks = [p for p in el.sink_pads if p.is_linked]
    linked_srcs = [p for p in el.src_pads if p.is_linked]
    if (len(el.sink_pads) != 1 or len(el.src_pads) != 1
            or len(linked_sinks) != 1 or len(linked_srcs) != 1):
        return ("fan-in/fan-out (a fused segment needs exactly one linked "
                "sink and one linked src pad)")
    return None


@dataclass
class SegmentPlan:
    """Result of :func:`plan_segments`: the fusable runs and, for every
    non-member, why it broke a chain."""

    segments: List[List["Element"]] = field(default_factory=list)
    barriers: Dict[str, str] = field(default_factory=dict)

    def describe(self) -> str:
        lines = []
        for seg in self.segments:
            lines.append(" -> ".join(el.name for el in seg))
        return "; ".join(lines) if lines else "(no fused segments)"


def plan_segments(pipeline: "Pipeline", min_run: int = 2) -> SegmentPlan:
    """Partition the graph into maximal linear runs of fusable device
    elements. Pure topology — nothing is traced, no backend is touched —
    so the static linter runs this on parsed-not-started pipelines too.
    Runs shorter than ``min_run`` elements are not segments — the default
    2 because a single dispatch is already a single dispatch; the
    placement planner (runtime/placement.py) passes 1, since a lone
    device element between queues is still a pipeline *stage* that needs
    a chip."""
    plan = SegmentPlan()
    members: Dict[int, bool] = {}
    for el in pipeline.elements.values():
        reason = barrier_reason(el)
        if reason is not None:
            plan.barriers[el.name] = reason
        else:
            members[id(el)] = True

    def next_member(el: "Element") -> Optional["Element"]:
        for pad in el.src_pads:
            if pad.peer is not None:
                nxt = pad.peer.element
                return nxt if id(nxt) in members else None
        return None

    def prev_member(el: "Element") -> Optional["Element"]:
        for pad in el.sink_pads:
            if pad.peer is not None:
                prv = pad.peer.element
                return prv if id(prv) in members else None
        return None

    visited: set = set()
    for el in pipeline.elements.values():
        if id(el) not in members or id(el) in visited:
            continue
        # rewind to the head of this run (bounded to the member count so a
        # pure-device cycle cannot spin the rewind; the cycle itself is
        # rejected after the forward walk below)
        head = el
        hops = 0
        while hops <= len(members):
            prv = prev_member(head)
            if prv is None or id(prv) in visited or prv is el:
                break
            head = prv
            hops += 1
        seg: List["Element"] = []
        cur: Optional["Element"] = head
        while cur is not None and id(cur) in members and id(cur) not in visited:
            visited.add(id(cur))
            seg.append(cur)
            cur = next_member(cur)
        # a pure-device ring linearizes to a run whose tail feeds a
        # member again (cur stopped on 'already visited'): REJECT it — a
        # fused tail pushing back into its own head would recurse
        # unboundedly. (Such a ring is also unreachable by data — every
        # sink pad is consumed inside the ring — but the planner must not
        # rely on that.)
        if cur is not None and any(cur is m for m in seg):
            plan.barriers[seg[0].name] = "device-element cycle (not fusable)"
            continue
        if len(seg) >= min_run:
            plan.segments.append(seg)
    return plan


def _donation_safe(head: "Element") -> bool:
    """Whether the segment may donate its input arrays to XLA (so the
    upstream stage's output HBM is reused for the segment's own
    intermediates). Requires a direct device-affinity producer AND a
    fully single-owner upstream closure (see _DONATION_SAFE_CHAIN) —
    a tee'd, retained, or application-held buffer donated here would be
    deleted out from under its other reader."""
    producer = None
    for pad in head.sink_pads:
        if pad.peer is not None:
            producer = pad.peer.element
    if producer is None or producer.device_affinity() != "device":
        return False
    seen = set()
    stack = [producer]
    while stack:
        el = stack.pop()
        if id(el) in seen:
            continue
        seen.add(id(el))
        if el.ELEMENT_NAME not in _DONATION_SAFE_CHAIN:
            return False
        if sum(1 for p in el.src_pads if p.is_linked) != 1:
            return False
        for pad in el.sink_pads:
            if pad.peer is not None:
                stack.append(pad.peer.element)
    return True


class FusedSegment:
    """One linear run of device elements compiled to a single dispatch.

    The head element's ``_chain_guarded`` routes buffers here; interior
    elements keep their pads, caps negotiation, and event flow untouched
    (CAPS/EOS travel element-to-element exactly as unfused), only the
    per-buffer data path collapses. ``dispatch`` returns False when the
    segment cannot fuse at runtime (a member's stage is untraceable —
    e.g. a host-native or canary-routing backend): the caller falls back
    to the ordinary per-element chain until the next ``invalidate()``.
    """

    # sampled device-latency probe cadence: one blocking sync every N
    # dispatches keeps the per-segment latency estimate honest without
    # serializing the stream (same discipline as tensor_filter's
    # latency_sampling prop)
    PROBE_EVERY = 16

    def __init__(self, elements: List["Element"]):
        self.elements = list(elements)
        self.head = elements[0]
        self.tail = elements[-1]
        self.name = f"{self.head.name}..{self.tail.name}"
        # profiler series key: pipeline-prefixed + canonical member
        # names (positional aliases for auto-named elements), so
        # ProfileArtifact.capture slices one pipeline's attribution and
        # restarts/replicas of the same launch line produce the SAME
        # per-segment entry
        pipe = getattr(self.head, "pipeline", None)
        self._profile_key = (
            f"{pipe.name if pipe is not None else '?'}:"
            f"{obs_profile.canonical_base(self.head)}.."
            f"{obs_profile.canonical_base(self.tail)}")
        self._lock = named_lock(f"FusedSegment._lock:{self.name}")
        self._gen = 0            # guarded-by: _lock
        self._call: Optional[Callable] = None   # guarded-by: _lock (reads racy-ok)
        self._defused = False    # guarded-by: _lock (reads racy-ok)
        # placement (runtime/placement.py): the chip this segment's one
        # dispatch is pinned to (a jax Device; None = jax default). Set
        # at plan/replan time via set_device, consumed at _build — the
        # steady-state dispatch path never looks at it.
        self._device = None      # guarded-by: _lock
        # double-buffered host→device staging for PLACED segments only
        # (transport/staging.py): built lazily on the first placed
        # dispatch that sees host inputs; the default-device path —
        # where the jitted call's own argument conversion is the fastest
        # H2D — never builds one
        self._stager = None      # guarded-by: _lock (reads racy-ok)
        # calibration hook: placement installs a per-dispatch probe while
        # a calibration window is open; cleared when the plan lands. Only
        # consulted under obs_profile.ACTIVE (calibration keeps recording
        # on), so the profiling-off hot path pays nothing.
        self._placement_probe: Optional[Callable] = None
        # memory accounting (obs/memory.py): armed by _build, consumed by
        # the first dispatch of each trace generation WHILE accounting is
        # on — one AOT lowering per generation pulls the compiled
        # executable's memory_analysis() into the static-estimate plane.
        # Consulted only under obs_memory.ACTIVE: off = one short-circuit
        # (the dispatch read is racy-ok; the consume re-checks locked).
        self._mem_pending = False  # guarded-by: _lock (reads racy-ok)
        # host-side per-buffer gates (QoS throttle on member filters);
        # empty for pure transform chains, so the steady-state fused path
        # pays zero extra Python per hop
        self._gates = [
            el.fusion_gate for el in elements
            if type(el).fusion_gate is not Element.fusion_gate
        ]
        self._donate = _donation_safe(self.head)
        # AOT compile cache (nnstreamer_tpu/aot): the (cache, key, stage,
        # digest) identity of the artifact the current trace generation
        # was built against — what invalidate(evict_aot=True) evicts when
        # a model swap retires the generation. None = plain jit build.
        self._aot_built = None   # guarded-by: _lock
        self.stats = {
            "elements": len(self.elements),
            "dispatches": 0,
            "retraces": 0,
            "defused": 0,
            "aot_hits": 0,
            "aot_exports": 0,
            "total_s": 0.0,
            "probe_device_s": 0.0,
        }

    # -- cache control -------------------------------------------------------
    def invalidate(self, evict_aot: bool = False) -> None:
        """Drop the cached callable: caps renegotiation, hot model swap
        (``filter.commit_model``/``reload_model``), and restart paths call
        this so the next buffer re-resolves against current state. Also
        re-arms a defused segment (a canary router swapped back to a
        traceable primary re-fuses).

        ``evict_aot=True`` (the model-swap path) additionally evicts the
        retiring generation's AOT artifact from the compile cache — the
        old model's compiled program leaves disk with its backend. Caps
        events and placement changes pass False: the artifact stays for
        the restart/replica warm path (the rebuild re-keys anyway, so a
        kept artifact can never serve a stale model)."""
        with self._lock:
            self._gen += 1
            self._call = None
            self._defused = False
            built = self._aot_built
            if evict_aot:
                self._aot_built = None
        if evict_aot and built is not None:
            cache, key, stage, digest = built
            try:
                cache.evict(key, stage, digest)
            except OSError:  # a shared cache dir raced us; eviction is GC
                pass
        # the same events that invalidate the trace invalidate the
        # placement decision (caps renegotiation changes tensor sizes,
        # a hot swap changes the model's cost): tell the planner so the
        # rebuild below re-resolves against a fresh plan
        pipe = getattr(self.head, "pipeline", None)
        state = getattr(pipe, "_placement_state", None)
        if state is not None:
            state.mark_dirty()

    def set_device(self, device) -> None:
        """Pin this segment's dispatch to ``device`` (placement planner).
        A change drops the cached callable — the composed jit re-lowers
        with the new target's in_shardings on the next buffer."""
        with self._lock:
            if device is self._device:
                return
            self._device = device
            self._gen += 1
            self._call = None
            self._defused = False
            stager = self._stager
        if stager is not None:
            # staged slots live on the OLD chip: drop them and follow
            stager.retarget(device)

    @property
    def device(self):
        """The planner-assigned chip (None = jax default device)."""
        return self._device

    def _stage_placed(self, tensors):
        """Host→device staging for a placed dispatch (see dispatch())."""
        from ..transport.staging import DoubleBufferedStager

        s = self._stager
        if s is None:
            with self._lock:
                s = self._stager
                if s is None:
                    s = self._stager = DoubleBufferedStager(self._device)
        return s.stage(tensors)

    def _aot_resolve(self, composed: Callable, example_args: tuple,
                     pipe) -> Optional[Callable]:
        """AOT compile-cache consult (nnstreamer_tpu/aot): load this
        segment's exported program, or export the freshly composed one.
        Either way the segment then serves THROUGH the artifact — the
        exporting process and every warm restart run the identical
        StableHLO module (and share its persistent XLA cache entries).
        Returns None when the cache is off, the segment donates input
        buffers or is pinned to a device (an exported program can honor
        neither), or the stage refuses to lower — the caller falls back
        to plain ``jax.jit``, which is always correct."""
        from .. import aot

        cache = aot.default_cache()
        if cache is None:
            return None
        key = aot.pipeline_key(pipe) if pipe is not None else None
        if key is None:
            return None

        def guard(loaded):
            # serve through the artifact while it covers the buffer
            # shape; a buffer outside its avals (trailing dims varied
            # under flexible caps — only the batch dim is symbolic)
            # falls back to plain jit, which retraces per shape exactly
            # as the pre-AOT path did, instead of erroring mid-stream.
            # The verdict is memoized per signature: the aval walk runs
            # once per NEW shape, never per dispatch
            import jax

            fallback = None
            verdicts: dict = {}

            def serve(args):
                nonlocal fallback
                sig = tuple(
                    (getattr(x, "shape", None), getattr(x, "dtype", None))
                    for x in args)
                ok = verdicts.get(sig)
                if ok is None:
                    if len(verdicts) > 512:  # flexible streams: bound it
                        verdicts.clear()
                    ok = verdicts[sig] = loaded.compatible((args,))
                if ok:
                    return loaded.call(args)
                if fallback is None:
                    fallback = jax.jit(composed)
                return fallback(args)
            # _record_memory lowers the served program for its one-shot
            # estimate; the exported module is what actually runs, so
            # hand its jit through (a closure has no .lower of its own)
            serve.lower = loaded.call.lower
            return serve

        stage, digest = aot.segment_identity(self.elements)
        loaded = cache.load(key, stage, digest)
        if loaded is not None and loaded.compatible((example_args,)):
            with self._lock:
                self._aot_built = (cache, key, stage, digest)
            self.stats["aot_hits"] += 1
            return guard(loaded)
        try:
            blob, meta, fresh = aot.export_stage(
                composed, (example_args,), poly=True)
        except aot.ExportError as e:
            logger.info("fused segment %s: AOT export failed (%s) — "
                        "serving plain jit", self.name, e)
            return None
        cache.save(key, stage, digest, blob, meta)
        with self._lock:
            self._aot_built = (cache, key, stage, digest)
        self.stats["aot_exports"] += 1
        logger.info("fused segment %s: exported %s AOT artifact "
                    "(%d bytes) for stage %s", self.name,
                    "shape-poly" if meta["poly"] else "static",
                    meta["nbytes"], stage)
        return guard(fresh)

    def _build(self, example_args: Optional[tuple] = None
               ) -> Optional[Callable]:
        import jax

        # a dirty placement plan (hot swap / caps event marked it) is
        # re-resolved HERE, on the rebuild path — never per-buffer; the
        # refresh may retarget this segment's device before the gen
        # snapshot below, so the new callable lowers for the right chip
        pipe = getattr(self.head, "pipeline", None)
        state = getattr(pipe, "_placement_state", None)
        if state is not None:
            state.refresh_if_dirty()
        with self._lock:
            gen = self._gen
            device = self._device
        stages = []
        for el in self.elements:
            stage = el.fusion_stage()
            if stage is None:
                with self._lock:
                    if self._gen == gen:
                        self._defused = True
                        self.stats["defused"] += 1
                logger.info(
                    "fused segment %s: %s has no traceable stage — "
                    "falling back to per-element dispatch", self.name,
                    el.describe())
                return None
            stages.append(stage)

        # one tuple argument (not varargs): donate_argnums=(0,) then
        # donates the WHOLE input pytree regardless of tensor arity
        def composed(xs):
            for stage in stages:
                xs = stage(xs)
            return xs

        jitted = None
        if example_args is not None and not self._donate and device is None:
            # AOT path: donation aliases HBM in a way a deserialized
            # program cannot replicate, and a pinned segment must lower
            # for its assigned chip — both keep the plain-jit path below
            try:
                jitted = self._aot_resolve(composed, example_args, pipe)
            except Exception:  # noqa: BLE001 - cache trouble != data loss
                logger.exception(
                    "fused segment %s: AOT cache consult failed — "
                    "serving plain jit", self.name)
        if jitted is None:
            jit_kw: dict = {}
            if self._donate:
                jit_kw["donate_argnums"] = (0,)
            if device is not None:
                # placement: the composed dispatch lowers FOR the assigned
                # chip; explicit in_shardings also reshards committed inputs
                # arriving from an upstream stage's device (the cross-stage
                # hop moves device-to-device inside the jit call's C++ arg
                # processing — no Python-side device_put on the hot path)
                from jax.sharding import SingleDeviceSharding

                jit_kw["in_shardings"] = SingleDeviceSharding(device)
            jitted = jax.jit(composed, **jit_kw)
        # publish only if no invalidation raced the build (a commit_model
        # between stage resolution and here must win)
        with self._lock:
            if self._gen == gen and not self._defused and self._call is None:
                self._call = jitted
                self.stats["retraces"] += 1
                # arm the per-generation static memory estimate: the
                # first dispatch under obs_memory.ACTIVE records it
                self._mem_pending = True
        return jitted

    def _record_memory(self, call, args: tuple) -> None:
        """One-shot per trace generation (memory accounting on): lower
        the composed jit AOT for the observed signature and record its
        memory_analysis() channels plus the member models' param
        footprints. Runs once per (re)trace, never steady-state."""
        try:
            compiled = call.lower(args).compile()
        except Exception:  # noqa: BLE001 - backends without AOT lowering
            compiled = None
        params = 0
        for el in self.elements:
            backend = getattr(el, "backend", None)
            if backend is not None:
                params += obs_memory.backend_param_nbytes(backend)
        if compiled is not None:
            obs_memory.record_compiled(self._profile_key, "fused", compiled,
                                       param_bytes=params)
        else:
            obs_memory.record_stage(self._profile_key, "fused",
                                    param_bytes=params)

    # -- hot path ------------------------------------------------------------
    def dispatch(self, pad, buf: Buffer) -> bool:
        """Run the whole segment as one XLA dispatch; push the result from
        the tail's src pad. Returns False when defused (caller chains
        per-element instead). Outputs stay device-resident."""
        call = self._call
        if call is None:
            if self._defused:
                return False
            # the first buffer's tensors are the example signature the
            # AOT plane lowers/validates against (batch dim symbolic)
            call = self._build(tuple(buf.tensors))
            if call is None:
                return False
        for gate in self._gates:
            if not gate(buf):
                return True  # dropped (QoS throttle), buffer consumed
        args = tuple(buf.tensors)
        if self._device is not None and \
                any(not hasattr(t, "addressable_shards") for t in args):
            # placement-pinned segment with host inputs: ride the
            # two-slot stager so frame N+1's async put overlaps frame
            # N's device compute (transport/staging.py). Default-device
            # segments skip this — the jitted call's own C++ argument
            # conversion is the faster H2D there.
            args = tuple(self._stage_placed(args))
        t0 = clock_now()
        try:
            # NNS_XFERCHECK: the fused region is a pure-jit dispatch —
            # steady state must perform ZERO implicit device→host pulls
            # (the zero-copy contract's sentinel scope; a no-op module-
            # global check when the sanitizer is off)
            with _san.no_implicit_d2h(f"fused:{self.name}"):
                outs = call(args)
        except Exception as e:
            # an allocation failure must land in the flight ring WITH the
            # owning stage's name before the error path erases the context
            if obs_memory.looks_like_oom(e):
                pipe = getattr(self.head, "pipeline", None)
                obs_memory.record_alloc_failure(
                    self._profile_key, e,
                    pipeline=pipe.name if pipe is not None else None)
            raise
        # total_s gets ONLY the host-side dispatch time, even on probed
        # frames — same channel separation as the unfused filter (device
        # completion goes to probe_device_s)
        dt = clock_now() - t0
        if obs_memory.ACTIVE and self._mem_pending:
            with self._lock:  # once per trace generation, never steady state
                pending = self._mem_pending
                self._mem_pending = False
            if pending:
                self._record_memory(call, tuple(buf.tensors))
        st = self.stats
        st["dispatches"] += 1
        st["total_s"] += dt
        if obs_quality.ACTIVE and \
                st["dispatches"] % obs_quality.SAMPLE_EVERY == 0:
            # data-plane health tap (obs/quality.py): one small jitted
            # reduce per sampled output tensor, device-side — the fused
            # chain is observed without defusing and without pulling
            # the full output to the host
            obs_quality.record_fused_outputs(self._profile_key, outs)
        probed = st["dispatches"] % self.PROBE_EVERY == 0
        if probed:
            for o in outs:
                if hasattr(o, "block_until_ready"):
                    # nnlint: disable=NNL101 — sampled latency probe: one
                    # blocking sync every PROBE_EVERY dispatches, by contract
                    o.block_until_ready()
            st["probe_device_s"] = clock_now() - t0
        if obs_profile.ACTIVE:
            # continuous profiler: per-segment host dispatch time every
            # buffer, device-complete latency on probed frames — the
            # per-segment attribution profile artifacts persist
            obs_profile.record_fused(
                self._profile_key, dt,
                device_s=st["probe_device_s"] if probed else None)
            # placement calibration (runtime/placement.py): the planner's
            # probe decides when enough samples landed to close the
            # calibration window and re-plan from the measured profile
            cb = self._placement_probe
            if cb is not None:
                cb(self)
        if trace.ACTIVE:
            trace.notify_fused(self.name, t0, dt,
                               {"elements": len(self.elements)})
        if obs_context.TRACING:
            parent = buf.meta.get("trace")
            if parent is not None:
                # the request's span context rode in on the buffer meta:
                # the single-dispatch chain becomes a child span in the
                # SAME trace as the client/fabric/batch spans
                obs_context.record_span(
                    f"fused:{self.name}", kind="fused", parent=parent,
                    start_s=t0, dur_s=dt,
                    attrs={"elements": len(self.elements)})
        out = Buffer(list(outs)).copy_metadata_from(buf)
        self.tail.push(out)
        return True

    def __repr__(self):
        return f"FusedSegment<{self.name} n={len(self.elements)}>"


def install(pipeline: "Pipeline") -> SegmentPlan:
    """Plan and annotate: called from ``Pipeline.play()`` after flow reset,
    before elements start. Idempotent — a replay re-plans from scratch."""
    uninstall(pipeline)
    plan = plan_segments(pipeline)
    segments: List[FusedSegment] = []
    for elements in plan.segments:
        seg = FusedSegment(elements)
        for el in elements:
            el._fusion_member = seg
        elements[0]._fusion_head = seg
        segments.append(seg)
    pipeline._fused_segments = segments
    if segments:
        # fused pipelines join the metrics plane: each segment's
        # dispatch/retrace/defuse counters render at GET /metrics
        obs_metrics.track_pipeline(pipeline)
        logger.info("pipeline %s: fused %d device segment(s): %s",
                    pipeline.name, len(segments), plan.describe())
    return plan


def uninstall(pipeline: "Pipeline") -> None:
    """Clear every fusion annotation (``fuse=False`` replays, teardown)."""
    for el in pipeline.elements.values():
        el._fusion_member = None
        el._fusion_head = None
    pipeline._fused_segments = []
