"""Declarative (structured) pipeline descriptions ↔ launch text (L6).

Reference analog: ``tools/development/parser/`` — the flex/bison pbtxt ↔
gst-launch converter (grammar.y), i.e. a machine-readable pipeline format
that round-trips with the launch-text UX. Ours is JSON-native::

    {
      "name": "detect",
      "elements": [
        {"factory": "tensor_src", "name": "src",
         "props": {"num-buffers": 8, "dimensions": "3:224:224:1"}},
        {"factory": "tensor_filter", "name": "f",
         "props": {"framework": "jax", "model": "..."}},
        {"caps": "other/tensors,types=float32", "name": "cf"},
        {"factory": "tensor_sink", "name": "out"}
      ],
      "links": [["src", "f"], ["f", "cf"], ["cf", "out"]]
    }

Link endpoints are ``"element"`` or ``"element.pad"`` (request pads created
on demand, same as the launch DSL). ``caps`` entries are capsfilters; they
are inlined into the emitted launch text. With no explicit ``links``, the
elements form a linear chain in listed order.

API: :func:`pipeline_from_description`, :func:`description_to_launch`,
:func:`launch_to_description` (inverse), :func:`load_pipeline_file`.
"""
from __future__ import annotations

import json
import shlex
from typing import Dict, List, Optional

from .pipeline import Pipeline


def description_to_launch(desc: dict) -> str:
    """Structured description → launch string.

    Emission scheme: declare every element (with its name and props) as its
    own chain, then express each link as a ``src. ! dst.`` reference chain —
    valid launch syntax that survives arbitrary graph shapes (tees, muxes,
    multi-chain). Capsfilter entries cannot be name-referenced in launch
    text, so each one is inlined: ``src. ! <caps> ! dst.``.
    """
    elements = list(desc.get("elements", []))
    if not elements:
        raise ValueError("pipeline description has no elements")
    by_name: Dict[str, dict] = {}
    for i, e in enumerate(elements):
        if "factory" not in e and "caps" not in e:
            raise ValueError(f"element #{i} needs 'factory' or 'caps': {e}")
        name = e.get("name") or f"e{i}__auto"
        e = {**e, "name": name}
        elements[i] = e
        if name in by_name:
            raise ValueError(f"duplicate element name '{name}'")
        by_name[name] = e

    links = [tuple(ln) for ln in (desc.get("links") or [])]
    if not links and len(elements) > 1:
        names = [e["name"] for e in elements]
        links = list(zip(names, names[1:]))
    caps_names = {e["name"] for e in elements if "caps" in e}

    def decl(e: dict) -> str:
        parts = [e["factory"], f"name={e['name']}"]
        for k, v in (e.get("props") or {}).items():
            v = _prop_str(v)
            parts.append(f"{k}={shlex.quote(v) if _needs_quote(v) else v}")
        return " ".join(parts)

    def ref(endpoint: str) -> str:
        return endpoint if "." in endpoint else endpoint + "."

    chunks = [decl(e) for e in elements if e["name"] not in caps_names]
    consumed: set = set()
    for i, (s, d) in enumerate(links):
        if i in consumed:
            continue
        s_el, d_el = s.split(".")[0], d.split(".")[0]
        if s_el in caps_names:
            continue  # emitted by its upstream link below
        if s_el not in by_name or d_el not in by_name:
            missing = s_el if s_el not in by_name else d_el
            raise ValueError(f"link references unknown element '{missing}'")
        if d_el in caps_names:
            follow = next(
                (j for j, (s2, _) in enumerate(links)
                 if j not in consumed and s2.split(".")[0] == d_el), None)
            if follow is None:
                raise ValueError(f"capsfilter '{d_el}' has no outgoing link")
            consumed.add(follow)
            chunks.append(
                f"{ref(s)} ! {by_name[d_el]['caps']} ! {ref(links[follow][1])}")
        else:
            chunks.append(f"{ref(s)} ! {ref(d)}")
    return " ".join(chunks)


def launch_to_description(launch: str) -> dict:
    """Launch string → structured description (the parser tool's
    gst-launch → pbtxt direction)."""
    from .parse import parse_launch

    pipe = parse_launch(launch)
    desc: dict = {"elements": [], "links": []}
    for name, el in pipe.elements.items():
        if el.ELEMENT_NAME == "capsfilter":
            entry: dict = {"caps": str(el.filter_caps), "name": name}
        else:
            entry = {"factory": el.ELEMENT_NAME, "name": name}
            props = {}
            for k, v in el.props.items():
                # _prop_defs is the MRO-merged table (class PROPERTIES
                # dicts shadow, e.g. the universal `silent`)
                default = el._prop_defs[k].default if k in el._prop_defs else None
                if v != default:
                    props[k.replace("_", "-")] = v
            if props:
                entry["props"] = props
        desc["elements"].append(entry)
        for pad in el.src_pads:
            if pad.peer is not None:
                desc["links"].append(
                    [f"{name}.{pad.name}",
                     f"{pad.peer.element.name}.{pad.peer.name}"])
    return desc


def pipeline_from_description(desc: dict) -> Pipeline:
    """Instantiate a Pipeline from a structured description."""
    from .parse import parse_launch

    return parse_launch(description_to_launch(desc))


def load_pipeline_file(path: str) -> Pipeline:
    """Load a ``.json`` structured description (or a launch-text file)."""
    from .parse import parse_launch

    with open(path) as fh:
        text = fh.read()
    if path.endswith(".json"):
        return pipeline_from_description(json.loads(text))
    return parse_launch(text.strip())


def _prop_str(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _needs_quote(v: str) -> bool:
    return v == "" or any(c in v for c in " !\"'")
