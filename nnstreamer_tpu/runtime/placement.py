"""Profile-guided cross-device segment placement compiler (L5).

PR 5's fusion compiler collapses linear device runs into one-dispatch
segments; PR 8's continuous profiler persists what each segment, element
hop, and queue wait actually *costs* as ``ProfileArtifact``s keyed by
(topology hash, caps, model version). This module closes the loop the
multi-TPU paper says dominates end-to-end latency — profiled model
segmentation and placement (arxiv 2503.01025), with the memory-aware
pipelined-placement stance of Hermes (arxiv 2409.04249): a **planner**
that reads a :class:`~nnstreamer_tpu.obs.profile.ProfileStore` and
assigns the fused segments of a pipeline across the devices of the local
mesh (``parallel/mesh.py`` order), then sizes the inter-stage ``queue``
depths from the same profile's queue-wait digests.

The plan algebra:

* **stages** — ``fusion.plan_segments(min_run=1)``: every maximal linear
  run of fusable device elements, *including* runs of one (a lone
  ``tensor_filter`` between queues is still a pipeline stage that needs
  a chip). Stage keys are canonical (positional aliases for auto-named
  elements), so the same launch line maps onto the same artifact entries
  across restarts and replicas.
* **costs** — per-stage latency from the artifact, best channel first:
  ``fused_device`` (sampled device-complete) → ``fused`` (host
  dispatch) → sum of ``element`` hops → a uniform per-element heuristic
  when nothing matches (the *calibration* path below).
* **assignment** — minimize the max per-device load so no chip carries
  more than ~1/N of the critical path when costs allow: exact search
  for realistic stage counts (the planner's choice provably matches the
  best hand placement over the same cost table), LPT
  (longest-processing-time-first) beyond that. Memory rides along as an
  opt-in ``max_stages_per_device`` cap (each stage's params +
  activations are chip-resident; HBM-constrained deployments bound how
  many stages may co-reside).
* **queue depths** — ``depth = clamp(ceil(p99_wait / downstream_p50) +
  1, min, max)``: deep enough to absorb the observed p99 wait burst at
  the downstream stage's service rate, shallow enough to bound memory
  and queued latency. Applied via ``QueueElement.set_capacity`` (counted
  in the queue's ``retuned`` stat); queues without profile data keep
  their user-set depth.
* **shard weights** — ``tensor_shard`` fan-outs get branch weights
  inverse to the profiled per-branch downstream cost, so a slow branch
  receives proportionally fewer frames (``TensorShard.set_branch_
  weights``).

Wiring: ``Pipeline(place="auto")`` / ``parse_launch(place=...)`` plans
at every ``play()`` (so a supervised restart re-plans from scratch, same
contract as fusion); a :class:`PlacementPlan` instance passed as
``place=`` applies a serialized plan verbatim (the autoscaler/AOT-cache
consumers of ROADMAP items 4/5). ``NNS_NO_PLACE=1`` is the kill switch.
Re-planning rides the SAME invalidation events fusion already handles:
``FusedSegment.invalidate`` (caps renegotiation, ``commit_model``/
``reload_model`` hot swaps) marks the plan dirty and the next segment
*rebuild* — never the per-buffer path — refreshes it.

Calibration fallback: when no artifact matches the pipeline's key and a
store or profiler is available, the planner installs a deterministic
heuristic plan, opens a refcounted recording window
(``obs.profile.begin_calibration``), and a per-dispatch probe on the
fused segments closes the window once every segment has seen
``CALIBRATION_DISPATCHES`` buffers: the live profile is captured,
saved to the store (``save(merge=True)``), and the plan is recomputed
from measurements — all on the rebuild/probe path, off steady state.

Observability: each plan lands as a ``placement`` span, the
``nns_placement_*`` gauges (stage→device, stage cost, queue depth,
balance ratio, replans), and a PLACEMENT section in ``obs top``.
See docs/placement.md.
"""
from __future__ import annotations

import math
import time
import weakref
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence

from ..analysis.sanitizer import named_lock
from ..obs import context as obs_context
from ..obs import flight as obs_flight
from ..obs import memory as obs_memory
from ..obs import metrics as obs_metrics
from ..obs import profile as obs_profile
from ..utils.log import logger
from . import fusion

if TYPE_CHECKING:
    from .pipeline import Pipeline

SCHEMA_VERSION = 1

#: fused dispatches per segment before a calibration window closes and
#: the plan is recomputed from the measured profile (3 sampled device
#: probes at the segment's PROBE_EVERY=16 cadence)
CALIBRATION_DISPATCHES = 48

#: planner-tuned queue depth bounds: deep enough for real jitter, never
#: deeper than memory/latency sanity allows
MIN_QUEUE_DEPTH = 2
MAX_QUEUE_DEPTH = 64

#: uniform per-element stage cost (ms) when nothing is profiled — only
#: RELATIVE costs matter to the assignment, so any constant works; 1 ms
#: keeps heuristic plans human-readable
HEURISTIC_ELEMENT_MS = 1.0


# ---------------------------------------------------------------------------
# plan model (serializable — ROADMAP items 4/5 ship these to replicas)
# ---------------------------------------------------------------------------

@dataclass
class StagePlacement:
    """One stage's assignment: ``stage`` is the canonical segment key
    (``head..tail`` for fused runs, the element's canonical name for
    singletons), ``device`` an index into :attr:`PlacementPlan.devices`.
    ``bytes`` is the stage's profiled static memory footprint (params +
    temp + output + argument + code, from the artifact's ``memory``
    section — obs/memory.py); 0 = unprofiled, unconstrained."""

    stage: str
    elements: List[str]
    device: int
    cost_ms: float
    p99_ms: float
    source: str  # "profile" | "heuristic"
    bytes: int = 0

    def to_dict(self) -> dict:
        return {"stage": self.stage, "elements": list(self.elements),
                "device": self.device, "cost_ms": round(self.cost_ms, 6),
                "p99_ms": round(self.p99_ms, 6), "source": self.source,
                "bytes": int(self.bytes)}

    @classmethod
    def from_dict(cls, d: dict) -> "StagePlacement":
        return cls(str(d["stage"]), [str(e) for e in d.get("elements", [])],
                   int(d["device"]), float(d.get("cost_ms", 0.0)),
                   float(d.get("p99_ms", 0.0)),
                   str(d.get("source", "heuristic")),
                   int(d.get("bytes", 0)))


@dataclass
class PlacementPlan:
    """A complete, serializable placement decision for one topology.

    ``devices`` are labels (``platform:id``) in local mesh order — the
    *indices* are what applies; a plan shipped to a replica with the
    same device count applies verbatim. ``queues`` maps canonical queue
    names to tuned depths, ``shard_weights`` maps ``tensor_shard`` names
    to per-branch weights."""

    pipeline: str = ""
    key: Dict[str, str] = field(default_factory=dict)
    devices: List[str] = field(default_factory=list)
    stages: List[StagePlacement] = field(default_factory=list)
    queues: Dict[str, dict] = field(default_factory=dict)
    shard_weights: Dict[str, List[float]] = field(default_factory=dict)
    source: str = "heuristic"  # "profile" | "heuristic" | "explicit"
    balance: Dict[str, float] = field(default_factory=dict)
    # AOT compile-cache artifact refs: {stage id: artifact file basename}
    # for stages whose compiled program is already exported
    # (nnstreamer_tpu/aot). A plan shipped to a remote replica thereby
    # names the exact serialized compiled units its stages need — the
    # host reaches READY with neither local profiling nor compilation
    # (ROADMAP item 5 hand-off). Empty when the AOT plane is off.
    aot: Dict[str, str] = field(default_factory=dict)

    def stage_for(self, stage_key: str) -> Optional[StagePlacement]:
        for st in self.stages:
            if st.stage == stage_key:
                return st
        return None

    def to_dict(self) -> dict:
        return {
            "schema": SCHEMA_VERSION,
            "kind": "nns-placement",
            "pipeline": self.pipeline,
            "key": dict(self.key),
            "devices": list(self.devices),
            "stages": [s.to_dict() for s in self.stages],
            "queues": {k: dict(v) for k, v in sorted(self.queues.items())},
            "shard_weights": {k: list(v) for k, v
                              in sorted(self.shard_weights.items())},
            "source": self.source,
            "balance": dict(self.balance),
            "aot": dict(sorted(self.aot.items())),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PlacementPlan":
        if d.get("kind") != "nns-placement":
            raise ValueError("not a placement plan (kind != nns-placement)")
        return cls(
            pipeline=d.get("pipeline", ""),
            key=dict(d.get("key", {})),
            devices=[str(x) for x in d.get("devices", [])],
            stages=[StagePlacement.from_dict(s) for s in d.get("stages", [])],
            queues={str(k): dict(v)
                    for k, v in (d.get("queues") or {}).items()},
            shard_weights={str(k): [float(w) for w in v]
                           for k, v in (d.get("shard_weights") or {}).items()},
            source=d.get("source", "explicit"),
            balance=dict(d.get("balance", {})),
            aot={str(k): str(v) for k, v in (d.get("aot") or {}).items()},
        )

    def describe(self) -> str:
        parts = [f"{s.stage}->dev{s.device}" for s in self.stages]
        return "; ".join(parts) if parts else "(no stages)"


# ---------------------------------------------------------------------------
# stage keys / cost extraction
# ---------------------------------------------------------------------------

def stage_key(elements: Sequence) -> str:
    """Canonical artifact key for a run of elements: matches the fused
    profiler series (``head..tail``, pipeline prefix stripped) so plan
    stages line up with ProfileArtifact entries across restarts."""
    head = obs_profile.canonical_base(elements[0])
    if len(elements) == 1:
        return head
    return f"{head}..{obs_profile.canonical_base(elements[-1])}"


def _entry_quantiles(entry: Optional[dict]) -> Optional[tuple]:
    if not entry or not entry.get("count"):
        return None
    dig = entry["digest"]
    return (dig.quantile(0.5) * 1e3, dig.quantile(0.99) * 1e3)


def _stage_cost(artifact, elements: Sequence) -> tuple:
    """(p50_ms, p99_ms, source) for one stage. Channel preference:
    sampled device-complete latency, host dispatch time, element-hop
    sum, uniform heuristic — in that order of honesty."""
    if artifact is not None:
        key = stage_key(elements)
        for scope in ("fused_device", "fused"):
            q = _entry_quantiles(artifact.entries.get(scope, {}).get(key))
            if q is not None:
                return q[0], q[1], "profile"
        hops = artifact.entries.get("element", {})
        p50 = p99 = 0.0
        found = 0
        for el in elements:
            q = _entry_quantiles(hops.get(obs_profile.canonical_base(el)))
            if q is not None:
                p50 += q[0]
                p99 += q[1]
                found += 1
        if found == len(elements) and found > 0:
            return p50, p99, "profile"
    cost = HEURISTIC_ELEMENT_MS * len(elements)
    return cost, cost, "heuristic"


def _stage_bytes(artifact, elements: Sequence) -> int:
    """Profiled static memory footprint of one stage from the artifact's
    ``memory`` section (obs/memory.py): the fused-segment entry first,
    the sum of singleton member entries otherwise, 0 (= unconstrained)
    when nothing was captured."""
    mem = getattr(artifact, "memory", None) if artifact is not None else None
    if not mem:
        return 0
    cell = mem.get(stage_key(elements))
    if cell is not None:
        return int(cell.get("total_bytes", 0) or 0)
    total = 0
    for el in elements:
        cell = mem.get(obs_profile.canonical_base(el))
        if cell is not None:
            total += int(cell.get("total_bytes", 0) or 0)
    return total


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------

class Planner:
    """Turns (topology, ProfileStore) into a :class:`PlacementPlan`.

    Deterministic by construction: the same store contents and device
    list always yield an identical plan (stable stage order, stable LPT
    tie-breaks) — the property the plan-cache/AOT consumers and the
    determinism tests rely on."""

    def __init__(self, store: Optional[object] = None,
                 devices: Optional[Sequence] = None, mesh=None,
                 min_queue_depth: int = MIN_QUEUE_DEPTH,
                 max_queue_depth: int = MAX_QUEUE_DEPTH,
                 max_stages_per_device: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None):
        if mesh is not None and devices is not None:
            raise ValueError("pass devices OR mesh, not both")
        self._store = store
        self._devices = list(devices) if devices is not None else None
        self._mesh = mesh
        self.min_queue_depth = int(min_queue_depth)
        self.max_queue_depth = int(max_queue_depth)
        # legacy memory knob (opt-in): cap how many stages may co-reside
        # on one chip regardless of bytes. Superseded by the byte
        # constraint below whenever the artifact carries memory
        # estimates, but still honored for deployments that tuned it.
        self.max_stages_per_device = max_stages_per_device
        # the REAL memory constraint (PR 10, obs/memory.py): per-device
        # HBM budget in bytes. None = auto — per device, the backend's
        # reported ``memory_stats()['bytes_limit']`` when available
        # (TPU/GPU), else the NNS_HBM_BUDGET env budget, else
        # unconstrained. With a budget and profiled per-stage byte
        # estimates the planner derives the co-residency cap itself:
        # bin-pack on bytes as a feasibility constraint inside the
        # exact/LPT balance search — no stage-count knob needed.
        self.hbm_budget_bytes = hbm_budget_bytes

    # -- inputs --------------------------------------------------------------
    @property
    def store(self):
        if self._store is None:
            self._store = obs_profile.default_store()
        return self._store

    @property
    def devices(self) -> list:
        """Local device farm in mesh order (``parallel/mesh.py``: the
        flattened ``make_mesh`` layout, which for the known axes is just
        ``jax.devices()`` order)."""
        if self._devices is None:
            if self._mesh is not None:
                self._devices = [d for d in self._mesh.devices.flat]
            else:
                import jax

                self._devices = list(jax.devices())
        return self._devices

    def device_budgets(self) -> List[Optional[int]]:
        """Per-device HBM budget in bytes, aligned with :attr:`devices`:
        the explicit ``hbm_budget_bytes`` when given, else what the
        device's own allocator reports (``memory_stats()['bytes_limit']``),
        else the process budget (``NNS_HBM_BUDGET``); None entries are
        unconstrained."""
        fallback = obs_memory.default_budget_bytes()
        budgets: List[Optional[int]] = []
        for d in self.devices:
            b = self.hbm_budget_bytes
            if b is None:
                ms = getattr(d, "memory_stats", None)
                if ms is not None:
                    try:
                        b = (ms() or {}).get("bytes_limit")
                    except Exception:  # noqa: BLE001 - backend w/o stats
                        b = None
            if b is None:
                b = fallback
            budgets.append(int(b) if b else None)
        return budgets

    def artifact_for(self, pipeline: "Pipeline", model_version: str = ""):
        """The stored profile matching this pipeline's key: the exact
        (topology, caps, model version) first, then the same topology
        under ANY caps — a fresh process plans BEFORE negotiation has
        produced caps, and an artifact captured on the negotiated stream
        is keyed by them (the scan is sorted for determinism)."""
        store = self.store
        if store is None:
            return None
        topo = obs_profile.topology_hash(pipeline)
        for caps in (obs_profile._negotiated_caps(pipeline), ""):
            art = store.load({"topology": topo, "caps": caps,
                              "model_version": model_version})
            if art is not None:
                return art
        for entry in sorted(store.list(),
                            key=lambda e: (e.get("caps", ""),
                                           e.get("path", ""))):
            if (entry.get("topology") == topo
                    and entry.get("model_version", "") == model_version):
                try:
                    return obs_profile.ProfileArtifact.load(entry["path"])
                except (OSError, ValueError, KeyError):
                    continue
        return None

    #: pass as ``artifact=`` to record "the store was already consulted
    #: and missed" — plan() then skips its own lookup (install() would
    #: otherwise pay the store directory scan twice per play on a miss)
    NO_ARTIFACT = object()

    # -- planning ------------------------------------------------------------
    def plan(self, pipeline: "Pipeline", artifact=None,
             model_version: str = "") -> PlacementPlan:
        """Compute the placement for ``pipeline``. Pure function of
        (topology, artifact, devices) — applies nothing."""
        if artifact is Planner.NO_ARTIFACT:
            artifact = None
        elif artifact is None:
            artifact = self.artifact_for(pipeline, model_version)
        seg_plan = fusion.plan_segments(pipeline, min_run=1)
        devices = self.devices
        n_dev = max(1, len(devices))
        plan = PlacementPlan(
            pipeline=pipeline.name,
            key={"topology": obs_profile.topology_hash(pipeline),
                 "caps": obs_profile._negotiated_caps(pipeline),
                 "model_version": model_version},
            devices=[f"{getattr(d, 'platform', 'cpu')}:"
                     f"{getattr(d, 'id', i)}"
                     for i, d in enumerate(devices)],
        )

        costs: Dict[str, tuple] = {}
        for elements in seg_plan.segments:
            key = stage_key(elements)
            costs[key] = _stage_cost(artifact, elements)
            plan.stages.append(StagePlacement(
                stage=key,
                elements=[obs_profile.canonical_base(e) for e in elements],
                device=0, cost_ms=costs[key][0], p99_ms=costs[key][1],
                source=costs[key][2],
                bytes=_stage_bytes(artifact, elements)))
        plan.source = ("profile" if artifact is not None
                       and any(s.source == "profile" for s in plan.stages)
                       else "heuristic")

        budgets = self.device_budgets()
        load, dev_bytes, byte_feasible = self._assign(
            plan.stages, n_dev, budgets=budgets)

        critical = sum(s.cost_ms for s in plan.stages)
        max_load = max(load) if plan.stages else 0.0
        target = critical / n_dev if critical else 0.0
        plan.balance = {
            "critical_path_ms": round(critical, 6),
            "max_stage_ms": round(max_load, 6),
            "target_ms": round(target, 6),
            # 1.0 = perfectly balanced; a single dominant segment can
            # push this up — the planner cannot split inside a segment
            "ratio": round(max_load / target, 4) if target else 1.0,
            "n_devices": n_dev,
            # memory side (obs/memory.py): what the byte constraint saw
            "stage_bytes_total": sum(s.bytes for s in plan.stages),
            "max_device_bytes": max(dev_bytes) if dev_bytes else 0,
            "budget_bytes": min((b for b in budgets if b), default=0),
            "byte_feasible": byte_feasible,
        }

        self._tune_queues(pipeline, artifact, plan)
        self._shard_weights(pipeline, artifact, plan)
        # reference the compiled units: stages whose exported AOT
        # artifact already exists are named in the plan, so shipping the
        # plan + the named cache files to a remote host hands over both
        # the placement decision AND the compiled programs it places
        from .. import aot as aot_cache

        cache = aot_cache.default_cache()
        if cache is not None:
            refs = cache.stage_artifacts(plan.key.get("topology", ""))
            stages = {s.stage for s in plan.stages}
            plan.aot = {k: v for k, v in refs.items() if k in stages}
        return plan

    # makespan minimization (multiprocessor scheduling) is NP-hard in
    # general; real pipelines have a handful of stages, so up to this
    # many candidate assignments the planner just takes the exact
    # optimum (still << one XLA retrace on the rebuild path where
    # re-planning runs)
    EXACT_SEARCH_LIMIT = 65536

    def _assign(self, stages: List[StagePlacement], n_dev: int,
                budgets: Optional[Sequence[Optional[int]]] = None
                ) -> tuple:
        """Assign stages to devices minimizing the max per-device load
        under two feasibility constraints: the legacy (opt-in)
        ``max_stages_per_device`` count cap, and — when per-stage byte
        estimates and per-device budgets exist — the HBM **byte budget**
        (each stage's params + activations are resident on its chip, so
        the sum of co-resident stage bytes must fit the chip). Exact
        enumeration when the space is small — "auto matches the best
        hand placement among FEASIBLE assignments" is structural, not
        heuristic — LPT (longest-processing-time-first onto the
        least-loaded eligible device) beyond that. Deterministic: the
        exact path takes the lexicographically-smallest optimum in
        stage order; LPT breaks ties on stage key then device index.

        Returns ``(load_ms, device_bytes, byte_feasible)``. When no
        byte-feasible assignment exists at all (a stage alone outgrows
        every budget, or the packing cannot fit), the byte constraint is
        dropped with a warning + ``memory`` flight event — a plan MUST
        always come out — and ``byte_feasible`` reports False."""
        if not stages:
            return [0.0] * n_dev, [0] * n_dev, True
        budgets = (list(budgets) if budgets is not None
                   else [None] * n_dev)
        budgets += [None] * (n_dev - len(budgets))
        constrained = (any(b is not None for b in budgets)
                       and any(s.bytes for s in stages))
        result = self._assign_under(stages, n_dev,
                                    budgets if constrained else
                                    [None] * n_dev)
        if result is not None:
            load, dev_bytes = result
            return load, dev_bytes, self._fits(dev_bytes, budgets)
        # byte-infeasible everywhere: relax and report
        logger.warning(
            "placement: no byte-feasible assignment of %d stages "
            "(total %d bytes) under budgets %s — relaxing the memory "
            "constraint", len(stages), sum(s.bytes for s in stages),
            budgets)
        obs_flight.record("memory", "placement_infeasible",
                          {"stages": len(stages),
                           "stage_bytes": sum(s.bytes for s in stages),
                           "budgets": [b or 0 for b in budgets]})
        load, dev_bytes = self._assign_under(stages, n_dev,
                                             [None] * n_dev)
        return load, dev_bytes, False

    @staticmethod
    def _fits(dev_bytes: List[int],
              budgets: Sequence[Optional[int]]) -> bool:
        return all(b is None or used <= b
                   for used, b in zip(dev_bytes, budgets))

    def _assign_under(self, stages: List[StagePlacement], n_dev: int,
                      budgets: Sequence[Optional[int]]
                      ) -> Optional[tuple]:
        """One constrained search pass; None when the exact search finds
        no feasible assignment (only possible with byte budgets)."""
        cap = self.max_stages_per_device
        if cap is None:
            cap = len(stages)  # unconstrained
        cap = max(cap, math.ceil(len(stages) / n_dev))  # must always fit
        if n_dev ** len(stages) <= self.EXACT_SEARCH_LIMIT:
            import itertools

            best: Optional[tuple] = None
            for combo in itertools.product(range(n_dev), repeat=len(stages)):
                load = [0.0] * n_dev
                count = [0] * n_dev
                mem = [0] * n_dev
                ok = True
                for st, dev in zip(stages, combo):
                    count[dev] += 1
                    mem[dev] += st.bytes
                    if count[dev] > cap or (
                            budgets[dev] is not None
                            and mem[dev] > budgets[dev]):
                        ok = False
                        break
                    load[dev] += st.cost_ms
                if not ok:
                    continue
                key = (max(load), combo)
                if best is None or key < best:
                    best = key + (load, mem)
            if best is None:
                return None  # byte budgets forbade every assignment
            for st, dev in zip(stages, best[1]):
                st.device = dev
            return best[2], best[3]
        load = [0.0] * n_dev
        count = [0] * n_dev
        mem = [0] * n_dev
        over_budget = False
        for st in sorted(stages, key=lambda s: (-s.cost_ms, s.stage)):
            eligible = [i for i in range(n_dev)
                        if count[i] < cap
                        and (budgets[i] is None
                             or mem[i] + st.bytes <= budgets[i])]
            if not eligible:
                # no device has byte headroom: this greedy packing
                # failed — report None so _assign relaxes with the same
                # warning + flight event the exact path emits (greedy
                # LPT is a heuristic; a feasible packing may exist, but
                # a silently over-budget plan must never come out as
                # byte_feasible)
                over_budget = True
                eligible = [i for i in range(n_dev) if count[i] < cap]
            idx = min(eligible or range(n_dev), key=lambda i: (load[i], i))
            st.device = idx
            load[idx] += st.cost_ms
            count[idx] += 1
            mem[idx] += st.bytes
        if over_budget and any(b is not None for b in budgets):
            return None
        return load, mem

    def _tune_queues(self, pipeline: "Pipeline", artifact,
                     plan: PlacementPlan) -> None:
        """Size each queue from its profiled wait digest: the depth must
        hold the burst a p99 wait implies at the downstream stage's
        service rate; no profile ⇒ the user's depth stands."""
        if artifact is None:
            return
        waits = artifact.entries.get("queue_wait", {})
        # downstream stage p50 per queue: the first planned stage
        # reachable through the queue's src pad
        stage_of = {}
        for st in plan.stages:
            for el_name in st.elements:
                stage_of[el_name] = st
        mean_cost = ([s.cost_ms for s in plan.stages] or [HEURISTIC_ELEMENT_MS])
        fallback_ms = sum(mean_cost) / len(mean_cost)
        for el in pipeline.elements.values():
            if el.ELEMENT_NAME != "queue":
                continue
            canon = obs_profile.canonical_base(el)
            q = _entry_quantiles(waits.get(canon))
            if q is None:
                continue
            _, wait_p99_ms = q
            nxt = None
            for pad in el.src_pads:
                if pad.peer is not None:
                    nxt = stage_of.get(
                        obs_profile.canonical_base(pad.peer.element))
            service_ms = max(nxt.cost_ms if nxt is not None else fallback_ms,
                             1e-3)
            depth = int(math.ceil(wait_p99_ms / service_ms)) + 1
            depth = max(self.min_queue_depth,
                        min(self.max_queue_depth, depth))
            plan.queues[canon] = {
                "depth": depth,
                "wait_p99_ms": round(wait_p99_ms, 6),
                "service_ms": round(service_ms, 6),
            }

    def _shard_weights(self, pipeline: "Pipeline", artifact,
                       plan: PlacementPlan) -> None:
        """Weight ``tensor_shard`` branches inversely to their profiled
        downstream cost (a branch twice as slow gets half the frames)."""
        if artifact is None:
            return
        hops = artifact.entries.get("element", {})
        for el in pipeline.elements.values():
            if el.ELEMENT_NAME != "tensor_shard":
                continue
            branch_costs: List[float] = []
            for pad in el.src_pads:
                if pad.peer is None:
                    continue
                cost = 0.0
                cur = pad.peer.element
                seen = set()
                while cur is not None and id(cur) not in seen:
                    seen.add(id(cur))
                    if cur.ELEMENT_NAME == "tensor_unshard":
                        break
                    q = _entry_quantiles(
                        hops.get(obs_profile.canonical_base(cur)))
                    if q is not None:
                        cost += q[0]
                    nxt = None
                    for sp in cur.src_pads:
                        if sp.peer is not None:
                            nxt = sp.peer.element
                            break
                    cur = nxt
                branch_costs.append(cost)
            if len(branch_costs) >= 2 and all(c > 0 for c in branch_costs):
                inv = [1.0 / c for c in branch_costs]
                total = sum(inv)
                plan.shard_weights[el.name] = [round(w / total, 6)
                                               for w in inv]


# ---------------------------------------------------------------------------
# runtime wiring: per-pipeline state, apply, calibration, re-plan
# ---------------------------------------------------------------------------

class _PlacementState:
    """Everything placement hangs off one playing pipeline: the current
    plan, the dirty flag fusion's invalidation path sets, and the
    calibration window. Lock order: leaf under everything — taken bare,
    and takes only FusedSegment/queue locks sequentially via apply."""

    def __init__(self, pipeline: "Pipeline", planner: Planner,
                 plan: PlacementPlan, explicit: bool = False):
        self._pipe = weakref.ref(pipeline)
        self.planner = planner
        self.plan = plan
        # an explicit (serialized, user-supplied) plan is authoritative:
        # invalidation events re-APPLY it to the fresh segments, they
        # never recompute it away
        self.explicit = explicit
        self._lock = named_lock(f"PlacementState._lock:{pipeline.name}")
        self._dirty = False          # guarded-by: _lock
        self._calibrating = False    # guarded-by: _lock
        self.replans = 0             # guarded-by: _lock

    # -- invalidation (fusion calls these) -----------------------------------
    def mark_dirty(self) -> None:
        with self._lock:
            self._dirty = True

    def refresh_if_dirty(self) -> None:
        """Re-plan + re-apply if an invalidation event landed since the
        last plan. Runs on the segment REBUILD path (fusion._build), so
        the steady-state dispatch never pays for it."""
        with self._lock:
            if not self._dirty:
                return
            self._dirty = False
        pipe = self._pipe()
        if pipe is None:
            return
        self.replan(pipe)

    def replan(self, pipeline: "Pipeline") -> None:
        t0 = time.monotonic()
        if self.explicit:
            # authoritative plan: the invalidation replaced the fused
            # segments / backend state, so re-apply the SAME assignment
            with self._lock:
                plan = self.plan
                self.replans += 1
        else:
            plan = self.planner.plan(pipeline)
            with self._lock:
                self.plan = plan
                self.replans += 1
        _apply(pipeline, plan, self.planner.devices)
        _emit_plan(pipeline, plan, time.monotonic() - t0, replan=True)

    # -- calibration ---------------------------------------------------------
    def begin_calibration(self, pipeline: "Pipeline") -> None:
        segments = pipeline.fused_segments
        if not segments:
            return  # nothing produces fused samples; stay heuristic
        with self._lock:
            if self._calibrating:
                return
            self._calibrating = True
        obs_profile.begin_calibration()
        # byte estimates ride the same window: the artifact captured at
        # window close carries the memory section the auto-cap needs
        obs_memory.begin_calibration()
        for seg in segments:
            seg._placement_probe = self._calibration_probe
        logger.info("placement %s: no profile artifact — calibrating over "
                    "%d fused dispatches per segment", pipeline.name,
                    CALIBRATION_DISPATCHES)

    def _calibration_probe(self, seg) -> None:
        """Per-dispatch hook (only while obs recording is on): close the
        window once every probed segment has enough samples."""
        if seg.stats["dispatches"] < CALIBRATION_DISPATCHES:
            return
        pipe = self._pipe()
        if pipe is None:
            self.close()
            return
        if any(s.stats["dispatches"] < CALIBRATION_DISPATCHES
               for s in pipe.fused_segments):
            return
        self.finish_calibration(pipe)

    def finish_calibration(self, pipeline: "Pipeline") -> None:
        """Capture the measured profile, persist it, re-plan from it.
        Runs inline on the dispatching thread exactly once — planning is
        microseconds against a handful of stages."""
        with self._lock:
            if not self._calibrating:
                return
            self._calibrating = False
        for seg in pipeline.fused_segments:
            seg._placement_probe = None
        try:
            artifact = obs_profile.ProfileArtifact.capture(pipeline)
            store = self.planner.store
            if store is not None:
                store.save(artifact, merge=True)
            t0 = time.monotonic()
            plan = self.planner.plan(pipeline, artifact=artifact)
            with self._lock:
                self.plan = plan
                self.replans += 1
            _apply(pipeline, plan, self.planner.devices)
            _emit_plan(pipeline, plan, time.monotonic() - t0, replan=True)
            logger.info("placement %s: calibration complete — %s",
                        pipeline.name, plan.describe())
        finally:
            obs_profile.end_calibration()
            obs_memory.end_calibration()

    def close(self) -> None:
        """End-of-run cleanup: an open calibration window must not leak
        its recording refcount past stop()."""
        with self._lock:
            was = self._calibrating
            self._calibrating = False
        if was:
            pipe = self._pipe()
            for seg in (pipe.fused_segments if pipe is not None else []):
                seg._placement_probe = None
            obs_profile.end_calibration()
            obs_memory.end_calibration()

    def snapshot(self) -> dict:
        with self._lock:
            plan = self.plan
            replans = self.replans
            calibrating = self._calibrating
        out = plan.to_dict()
        out["replans"] = replans
        out["calibrating"] = calibrating
        return out


# ---------------------------------------------------------------------------
# apply / install / uninstall
# ---------------------------------------------------------------------------

def _apply(pipeline: "Pipeline", plan: PlacementPlan,
           devices: Sequence) -> None:
    """Push a plan into the live graph: fused-segment device pins
    (re-lowered lazily on the next buffer), singleton tensor_filter
    backend pins (consumed at backend open — user-explicit
    ``custom=device:N``/``mesh:`` always wins), tuned queue depths, and
    shard branch weights."""
    by_canon = {obs_profile.canonical_base(el): el
                for el in pipeline.elements.values()}
    placed = set()
    for seg in pipeline.fused_segments:
        st = plan.stage_for(stage_key(seg.elements))
        if st is None or st.device >= len(devices):
            continue
        seg.set_device(devices[st.device])
        placed.add(st.stage)
    for st in plan.stages:
        if st.stage in placed or len(st.elements) != 1:
            continue
        el = by_canon.get(st.elements[0])
        if el is not None and hasattr(el, "set_placement_device") \
                and st.device < len(devices):
            el.set_placement_device(_global_index(devices[st.device]))
    for canon, q in plan.queues.items():
        el = by_canon.get(canon)
        if el is not None and hasattr(el, "set_capacity"):
            el.set_capacity(int(q["depth"]))
    for name, weights in plan.shard_weights.items():
        el = pipeline.elements.get(name)
        if el is not None and hasattr(el, "set_branch_weights"):
            el.set_branch_weights(weights)


def _global_index(device) -> Optional[int]:
    """The ``jax.devices()`` index of a planner device. The backend pin
    (``custom=device:N``) addresses the GLOBAL farm — a planner built
    over a subset or reordered mesh must not leak its local index into
    it (fused segments are immune: they pin by device object)."""
    import jax

    for i, d in enumerate(jax.devices()):
        if d is device or d == device:
            return i
    return None  # device from another farm/process: leave unpinned


def _emit_plan(pipeline: "Pipeline", plan: PlacementPlan, plan_s: float,
               replan: bool = False) -> None:
    if obs_context.TRACING:
        obs_context.record_span(
            f"placement:plan:{pipeline.name}", kind="placement",
            start_s=time.monotonic() - plan_s, dur_s=plan_s,
            attrs={"stages": len(plan.stages),
                   "devices": plan.balance.get("n_devices", 0),
                   "source": plan.source, "replan": replan})
    logger.info("placement %s (%s%s): %s | queues %s", pipeline.name,
                plan.source, ", replan" if replan else "",
                plan.describe(),
                {k: v["depth"] for k, v in plan.queues.items()} or "untouched")


def install(pipeline: "Pipeline", planner: Optional[Planner] = None
            ) -> Optional[PlacementPlan]:
    """Plan + apply at ``play()`` (after ``fusion.install``). The
    ``place`` mode the pipeline carries decides the path: ``"auto"``
    plans from the store (calibrating on a miss), a
    :class:`PlacementPlan` instance applies verbatim (``explicit``)."""
    uninstall(pipeline)
    mode = getattr(pipeline, "place", None)
    if not mode:
        return None
    t0 = time.monotonic()
    planner = planner or Planner()
    explicit = isinstance(mode, PlacementPlan)
    if explicit:
        plan = mode
        plan.source = "explicit"
        artifact = True  # an explicit plan never calibrates
    else:
        artifact = planner.artifact_for(pipeline)
        plan = planner.plan(
            pipeline,
            artifact=artifact if artifact is not None
            else Planner.NO_ARTIFACT)
    state = _PlacementState(pipeline, planner, plan, explicit=explicit)
    pipeline._placement_state = state
    _apply(pipeline, plan, planner.devices)
    _track(pipeline)
    _emit_plan(pipeline, plan, time.monotonic() - t0)
    if artifact is None:
        state.begin_calibration(pipeline)
    return plan


def uninstall(pipeline: "Pipeline") -> None:
    """Drop placement state (closing any open calibration window) and
    clear per-element pins. Fused segments are re-created by
    ``fusion.install`` each play, so their pins die with them."""
    state = getattr(pipeline, "_placement_state", None)
    if state is not None:
        state.close()
    pipeline._placement_state = None
    for el in pipeline.elements.values():
        if hasattr(el, "set_placement_device"):
            el.set_placement_device(None)


def on_stop(pipeline: "Pipeline") -> None:
    """Pipeline.stop() hook: a calibration window must not outlive the
    run that was feeding it samples, and the stopped pipeline's
    ``nns_placement_*`` gauge rows leave the scrape immediately (the
    weak set alone keeps them visible until GC runs; install() at the
    next play re-tracks)."""
    state = getattr(pipeline, "_placement_state", None)
    if state is not None:
        state.close()
    _tracked_placed.discard(pipeline)


# ---------------------------------------------------------------------------
# observability: gauges collector + snapshot for /profile and obs top
# ---------------------------------------------------------------------------

_tracked_placed: "weakref.WeakSet" = weakref.WeakSet()

_G_STAGE_DEV = obs_metrics.gauge(
    "nns_placement_stage_device",
    "planner-assigned device index per pipeline stage",
    ("pipeline", "stage"))
_G_STAGE_COST = obs_metrics.gauge(
    "nns_placement_stage_cost_ms",
    "profiled (or heuristic) per-buffer stage cost the plan balanced",
    ("pipeline", "stage"))
_G_QUEUE_DEPTH = obs_metrics.gauge(
    "nns_placement_queue_depth",
    "planner-tuned inter-stage queue depth",
    ("pipeline", "queue"))
_G_BALANCE = obs_metrics.gauge(
    "nns_placement_balance_ratio",
    "max per-device load over the 1/N critical-path target (1.0 = balanced)",
    ("pipeline",))
_G_REPLANS = obs_metrics.gauge(
    "nns_placement_replans_total",
    "plan recomputations (calibration close, caps events, hot swaps)",
    ("pipeline",))


def _track(pipeline: "Pipeline") -> None:
    _tracked_placed.add(pipeline)


def _collect_placement(_registry) -> None:
    for g in (_G_STAGE_DEV, _G_STAGE_COST, _G_QUEUE_DEPTH, _G_BALANCE,
              _G_REPLANS):
        g.clear()
    for pipe in list(_tracked_placed):
        state = getattr(pipe, "_placement_state", None)
        if state is None:
            continue
        snap = state.snapshot()
        for st in snap["stages"]:
            _G_STAGE_DEV.set(st["device"], pipeline=pipe.name,
                             stage=st["stage"])
            _G_STAGE_COST.set(st["cost_ms"], pipeline=pipe.name,
                              stage=st["stage"])
        for qname, q in snap["queues"].items():
            _G_QUEUE_DEPTH.set(q["depth"], pipeline=pipe.name, queue=qname)
        _G_BALANCE.set(snap["balance"].get("ratio", 1.0), pipeline=pipe.name)
        _G_REPLANS.set(snap["replans"], pipeline=pipe.name)


obs_metrics.register_collector("placement", _collect_placement)


def snapshot_all() -> List[dict]:
    """Plans of every live placed pipeline — the ``placement`` block of
    ``GET /profile`` and the PLACEMENT section of ``obs top``."""
    out = []
    for pipe in list(_tracked_placed):
        state = getattr(pipe, "_placement_state", None)
        if state is not None:
            out.append(state.snapshot())
    return sorted(out, key=lambda d: d.get("pipeline", ""))
