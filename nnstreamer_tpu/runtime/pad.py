"""Pads: typed, linkable stream endpoints on elements (L0' substrate).

Reference analog: GstPad/GstPadTemplate — every reference element declares
static pad templates with caps (e.g. ``gst/nnstreamer/elements/gsttensor_converter.c``
sink/src templates) and data flows by ``gst_pad_push``. Our model keeps the
push semantics (caller's thread runs the downstream chain until a queue
boundary) and event-driven caps negotiation: a fixed CAPS event travels
downstream ahead of the first buffer.

Fusion note: when the peer element heads a fused device segment
(``runtime/fusion.py``), ``push`` still enters through the peer's
``_chain_guarded`` — but the whole segment then runs as ONE XLA dispatch
and the next per-element push happens at the segment *tail*. A traced
``notify_flow`` span at a segment head therefore covers the entire fused
chain (the interior hops no longer exist).
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from time import monotonic as _monotonic

from ..core import Buffer, Caps, Event, EventType
from ..utils import trace

if TYPE_CHECKING:
    from .element import Element


class PadDirection(enum.Enum):
    SINK = "sink"
    SRC = "src"


class PadPresence(enum.Enum):
    ALWAYS = "always"
    REQUEST = "request"   # mux/demux-style on-demand pads ("sink_%u")


@dataclass(frozen=True)
class PadTemplate:
    name_template: str           # "sink", "src", "sink_%u", ...
    direction: PadDirection
    caps: Caps
    presence: PadPresence = PadPresence.ALWAYS

    @property
    def is_request(self) -> bool:
        return self.presence is PadPresence.REQUEST


class Pad:
    """One endpoint. Sink pads receive, src pads push."""

    def __init__(self, element: "Element", template: PadTemplate, name: str):
        self.element = element
        self.template = template
        self.name = name
        self.direction = template.direction
        self.peer: Optional["Pad"] = None
        self.caps: Optional[Caps] = None       # negotiated, fixed
        self.got_eos = False

    # ------------------------------------------------------------------
    @property
    def full_name(self) -> str:
        return f"{self.element.name}.{self.name}"

    @property
    def is_linked(self) -> bool:
        return self.peer is not None

    def link(self, other: "Pad") -> None:
        if self.direction is not PadDirection.SRC or other.direction is not PadDirection.SINK:
            raise ValueError(f"link must be src->sink ({self.full_name} -> {other.full_name})")
        if self.peer is not None or other.peer is not None:
            raise ValueError(f"pad already linked: {self.full_name} or {other.full_name}")
        if not self.template.caps.can_intersect(other.template.caps):
            raise ValueError(
                f"incompatible pad templates: {self.full_name} ({self.template.caps}) "
                f"!-> {other.full_name} ({other.template.caps})"
            )
        self.peer = other
        other.peer = self

    # ------------------------------------------------------------------
    # data flow (src side)
    def push(self, buf: Buffer) -> None:
        """Push a buffer downstream; runs the peer element's chain inline."""
        assert self.direction is PadDirection.SRC, f"push on sink pad {self.full_name}"
        peer = self.peer
        if peer is None:
            return  # unlinked src pad silently drops (reference: not-linked flow)
        if trace.ACTIVE:  # zero-cost when tracing is off (GstShark analog)
            t0 = _monotonic()
            peer.element._chain_guarded(peer, buf)
            trace.notify_flow(self, buf, _monotonic() - t0)
            return
        peer.element._chain_guarded(peer, buf)

    def push_event(self, event: Event) -> None:
        """Send an in-band event downstream (CAPS/EOS/SEGMENT/FLUSH)."""
        assert self.direction is PadDirection.SRC
        if event.type is EventType.CAPS:
            self.caps = event.data["caps"]
        peer = self.peer
        if peer is None:
            return
        peer.element._handle_sink_event_guarded(peer, event)

    # upstream events (sink side, e.g. QoS throttle)
    def send_upstream(self, event: Event) -> None:
        assert self.direction is PadDirection.SINK
        peer = self.peer
        if peer is None:
            return
        peer.element.handle_src_event(peer, event)

    def __repr__(self):
        return f"Pad<{self.full_name} {self.direction.value}>"
