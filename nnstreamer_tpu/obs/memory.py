"""Device-memory accounting plane: per-stage HBM estimates + live bytes (L7).

The latency half of the obs plane (tracing PR 7, profiler/SLO PR 8) can
say WHERE time goes; nothing in the system can say where *bytes* go —
yet memory, not latency, is the binding constraint for pipelined
inference on constrained devices (Hermes, arxiv 2409.04249), and the
multi-TPU segmentation paper shows *profiled* per-segment footprints are
what make placement decisions transfer to real hardware (arxiv
2503.01025). This module is the byte-side twin of :mod:`.profile`:

* **static per-stage estimates** — every fused segment pulls
  ``compiled.memory_analysis()`` (temp + output + argument +
  generated-code bytes) off its already-lowered jit once per trace
  generation (``FusedSegment.dispatch`` → :func:`record_compiled`);
  singleton ``tensor_filter`` stages report the same channels from
  their backend's jit plus the model's **param footprint** (sum of leaf
  array nbytes, walked out of the model callable's closure). Estimates
  land in the :class:`MemoryAccountant` keyed by the same
  ``<pipeline>:<canonical-stage>`` series names the profiler uses, so
  ``ProfileArtifact.capture`` persists them under a ``memory`` section
  of the SAME (topology, caps, model-version) key — merge semantics are
  **max-watermark** per field (a footprint is a high-water mark, not a
  sum).

* **live accounting** — :func:`sample_devices` reads per-device live
  buffer bytes from the backend (``device.memory_stats()`` where the
  runtime provides it — TPU/GPU — falling back to summing
  ``jax.live_arrays()`` per device on CPU farms), tracks per-device
  watermarks, and records ``memory`` flight events on watermark
  crossings; queue occupancy bytes are derived at scrape time from
  ``QueueElement`` depth × the negotiated caps frame size; serving
  KV/batch state registers via :func:`track_serving` (the continuous LM
  engine's slot caches). Everything renders as ``nns_memory_*`` gauges
  on ``GET /metrics``, as ``GET /memory`` JSON, and as the MEMORY
  section of ``obs top``.

* **admission** — :class:`AdmissionGuard` gives the serving schedulers
  a projected-bytes gate: a request whose tensors would push tracked
  serving bytes past the watermark is shed with a typed
  ``MemoryPressureError`` at submit time instead of OOM-ing mid-batch.

Cost contract (gated by tools/microbench_overhead.py, same family as
tracing/profiler/placement): with accounting off every hook is ONE
module-global check (:data:`ACTIVE`); the static-estimate capture costs
one extra lowering per segment trace generation and runs only while
accounting is on (a placement calibration window or an explicit
``start()``), never on the steady-state dispatch path.

Consumers: the placement planner derives its per-device stage caps from
the artifact's byte estimates against the real HBM budget
(``runtime/placement.py`` — the ROADMAP item 1 follow-up), and the SLO
engine evaluates ``memory``-kind objectives (headroom fraction,
multi-window burn) from the sampled used-fraction series. See
docs/observability.md (Memory section).
"""
from __future__ import annotations

import os
import threading
import weakref
from typing import Dict, List, Optional, Tuple

from ..analysis import sanitizer as _san
from ..analysis.sanitizer import named_lock
from . import flight as obs_flight
from . import metrics as obs_metrics

# module-global fast path: the fused-dispatch / filter-open hooks check
# this and only this when accounting is off (the microbench gate
# measures it)
ACTIVE = False

#: env var naming a process-wide device byte budget (bytes) for farms
#: whose runtime reports no ``memory_stats`` (CPU meshes); unset = no
#: budget, used-fraction reads 0.0 and watermark events never fire
BUDGET_ENV = "NNS_HBM_BUDGET"

#: fraction of the budget at which a ``memory`` flight event fires
DEFAULT_WATERMARK = 0.9

# static-estimate byte channels, in artifact/gauge order
FIELDS = ("temp_bytes", "output_bytes", "argument_bytes",
          "generated_code_bytes", "param_bytes")


# ---------------------------------------------------------------------------
# byte extraction helpers
# ---------------------------------------------------------------------------

def compiled_bytes(compiled) -> Optional[dict]:
    """The static byte channels of a lowered+compiled jax executable
    (``jax.jit(f).lower(*args).compile()``): XLA's own accounting of
    temp scratch, outputs, arguments, and generated code. None when the
    backend exposes no ``memory_analysis`` (older runtimes)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - backend without the query
        return None
    if ma is None:
        return None
    out = {
        "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0) or 0),
        "output_bytes": int(getattr(ma, "output_size_in_bytes", 0) or 0),
        "argument_bytes": int(getattr(ma, "argument_size_in_bytes", 0) or 0),
        "generated_code_bytes": int(
            getattr(ma, "generated_code_size_in_bytes", 0) or 0),
    }
    return out


def callable_param_nbytes(fn, max_objects: int = 4096) -> int:
    """Sum of device/host array bytes reachable from ``fn``'s closure —
    the model's parameter footprint for callables that close over their
    weights (the jax backend's builtin:// and module:attr models, and
    ``lm_serving`` entries' partial-applied params). Bounded BFS over
    closure cells, functools.partial args, and container values; arrays
    are recognized by an ``nbytes`` attribute and deduplicated by id so
    shared leaves count once."""
    import functools

    seen: set = set()
    total = 0
    stack = [fn]
    while stack and len(seen) < max_objects:
        obj = stack.pop()
        if id(obj) in seen or obj is None:
            continue
        seen.add(id(obj))
        nbytes = getattr(obj, "nbytes", None)
        if isinstance(nbytes, int) and hasattr(obj, "dtype"):
            total += nbytes
            continue
        if isinstance(obj, dict):
            stack.extend(obj.values())
        elif isinstance(obj, (list, tuple, set, frozenset)):
            stack.extend(obj)
        elif isinstance(obj, functools.partial):
            stack.append(obj.func)
            stack.extend(obj.args)
            stack.extend(obj.keywords.values())
        elif callable(obj):
            closure = getattr(obj, "__closure__", None)
            for cell in closure or ():
                try:
                    stack.append(cell.cell_contents)
                except ValueError:  # empty cell
                    continue
    return total


def backend_param_nbytes(backend) -> int:
    """A filter backend's model parameter footprint: an explicit
    ``params`` pytree when the backend carries one, else the closure
    walk over its model callable (the jax backend's ``_fn``)."""
    if backend is None:
        return 0
    params = getattr(backend, "params", None)
    if params is not None:
        n = tree_nbytes(params)
        if n:
            return n
    return callable_param_nbytes(getattr(backend, "_fn", None))


def tree_nbytes(tree) -> int:
    """Sum of leaf array nbytes of a pytree (params dicts, KV caches)."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:  # noqa: BLE001 - non-pytree / jax unavailable
        leaves = tree if isinstance(tree, (list, tuple)) else [tree]
    total = 0
    for leaf in leaves:
        nbytes = getattr(leaf, "nbytes", None)
        if isinstance(nbytes, int):
            total += nbytes
    return total


def caps_frame_nbytes(caps) -> int:
    """Bytes of ONE negotiated frame: sum over the caps' static tensor
    specs of prod(shape) × dtype size. 0 for flexible/unknown caps (the
    queue-occupancy estimate then reports depth only)."""
    if caps is None:
        return 0
    try:
        import numpy as np

        from ..core import TensorFormat, tensors_info_from_caps

        info = tensors_info_from_caps(caps)
        if info.format is not TensorFormat.STATIC:
            return 0
        total = 0
        for spec in info.specs:
            n = 1
            for d in spec.shape:
                n *= int(d)
            dtype = getattr(spec.dtype, "np_dtype", spec.dtype)
            total += n * np.dtype(dtype).itemsize
        return total
    except Exception:  # noqa: BLE001 - media caps, partial negotiation
        return 0


# ---------------------------------------------------------------------------
# the accountant (static per-stage estimates)
# ---------------------------------------------------------------------------

class MemoryAccountant:
    """Process-wide static-estimate store. Entries are keyed like the
    profiler's duration series (``<pipeline>:<canonical-stage>`` for
    stages, the model URI for registry-slot footprints) and every byte
    field keeps the MAXIMUM ever recorded — a footprint is a watermark,
    so re-traces, restarts, and replica merges take the high-water
    reading, never a sum."""

    def __init__(self):
        self._lock = named_lock("MemoryAccountant._lock")
        # {name: {"kind": str, <FIELDS>: int, "total_bytes": int}}
        self._stages: Dict[str, dict] = {}   # guarded-by: _lock
        self._models: Dict[str, int] = {}    # guarded-by: _lock

    def record_stage(self, name: str, kind: str, **bytes_fields) -> None:
        with self._lock:
            cell = self._stages.get(name)
            if cell is None:
                cell = self._stages[name] = {"kind": kind}
                for f in FIELDS:
                    cell[f] = 0
            for f in FIELDS:
                v = int(bytes_fields.get(f, 0) or 0)
                if v > cell[f]:
                    cell[f] = v
            cell["total_bytes"] = sum(cell[f] for f in FIELDS)

    def record_model(self, name: str, param_bytes: int) -> None:
        """Registry-slot / model-URI param footprint (prepare_model and
        backend open both report here): max-watermark like stages."""
        with self._lock:
            if param_bytes > self._models.get(name, 0):
                self._models[name] = int(param_bytes)

    def stage(self, name: str) -> Optional[dict]:
        with self._lock:
            cell = self._stages.get(name)
            return dict(cell) if cell is not None else None

    def stages(self, prefix: str = "") -> Dict[str, dict]:
        """Stage entries, optionally restricted to one pipeline's prefix
        (``ProfileArtifact.capture`` strips it, same as durations)."""
        with self._lock:
            return {name: dict(cell) for name, cell in self._stages.items()
                    if name.startswith(prefix)}

    def models(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._models)

    def reset(self) -> None:
        with self._lock:
            self._stages.clear()
            self._models.clear()


default_accountant = MemoryAccountant()


def accountant() -> MemoryAccountant:
    return default_accountant


# -- hot call sites (each caller checks ACTIVE first) -------------------------

def record_compiled(name: str, kind: str, compiled,
                    param_bytes: int = 0) -> None:
    """Record a stage's static estimate from a compiled executable
    (fused segments pass the jit wrapper's AOT-compiled form)."""
    fields = compiled_bytes(compiled) or {}
    fields["param_bytes"] = param_bytes
    default_accountant.record_stage(name, kind, **fields)


def record_stage(name: str, kind: str, **bytes_fields) -> None:
    default_accountant.record_stage(name, kind, **bytes_fields)


def record_model_params(name: str, param_bytes: int) -> None:
    default_accountant.record_model(name, param_bytes)


def record_alloc_failure(stage: str, error: BaseException,
                         pipeline: Optional[str] = None) -> None:
    """An allocation/OOM-shaped failure with the owning stage's name —
    the flight-recorder breadcrumb a postmortem needs (always recorded,
    like every flight event; the caller re-raises)."""
    obs_flight.record("memory", "alloc_failure",
                      {"stage": stage,
                       "error": f"{type(error).__name__}: {error}"[:200]},
                      pipeline=pipeline)


def looks_like_oom(error: BaseException) -> bool:
    """Heuristic: is this exception an allocation failure? XLA surfaces
    RESOURCE_EXHAUSTED; host paths raise MemoryError."""
    if isinstance(error, MemoryError):
        return True
    text = str(error)
    return ("RESOURCE_EXHAUSTED" in text or "Out of memory" in text
            or "out of memory" in text)


# ---------------------------------------------------------------------------
# live device sampling + watermarks
# ---------------------------------------------------------------------------

def default_budget_bytes() -> Optional[int]:
    """The configured per-device byte budget (``NNS_HBM_BUDGET``), or
    None. Device-reported limits (``memory_stats()['bytes_limit']``)
    take precedence per device in :func:`sample_devices`."""
    raw = os.environ.get(BUDGET_ENV, "").strip()
    if not raw:
        return _configured_budget
    try:
        return int(float(raw))
    except ValueError:
        return _configured_budget


_configured_budget: Optional[int] = None


def set_budget(budget_bytes: Optional[int]) -> None:
    """Programmatic budget override (tests, embedded deployments); the
    env var wins when both are set."""
    global _configured_budget
    _configured_budget = (int(budget_bytes)
                          if budget_bytes is not None else None)


class _DeviceWatermarks:
    """Per-device high-water marks + crossing-state for flight events."""

    def __init__(self):
        self._lock = named_lock("_DeviceWatermarks._lock")
        self._peak: Dict[str, int] = {}      # guarded-by: _lock
        self._crossed: Dict[str, bool] = {}  # guarded-by: _lock

    def update(self, label: str, bytes_in_use: int,
               budget: Optional[int], watermark: float) -> int:
        """Fold one sample; returns the device's peak. Watermark
        crossings (both directions) land as ``memory`` flight events."""
        with self._lock:
            peak = self._peak.get(label, 0)
            if bytes_in_use > peak:
                peak = self._peak[label] = bytes_in_use
            was = self._crossed.get(label, False)
            now = bool(budget) and bytes_in_use > watermark * budget
            self._crossed[label] = now
        if now and not was:
            obs_flight.record("memory", "watermark",
                              {"device": label, "bytes": bytes_in_use,
                               "budget": budget, "watermark": watermark})
        elif was and not now:
            obs_flight.record("memory", "watermark_clear",
                              {"device": label, "bytes": bytes_in_use,
                               "budget": budget})
        return peak

    def peaks(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._peak)

    def reset(self) -> None:
        with self._lock:
            self._peak.clear()
            self._crossed.clear()


_watermarks = _DeviceWatermarks()


def sample_devices(watermark: float = DEFAULT_WATERMARK) -> List[dict]:
    """One live sample per local device: ``bytes_in_use`` from the
    backend's allocator stats when the runtime provides them (TPU/GPU),
    else the sum of ``jax.live_arrays()`` nbytes resident on the device
    (exact for CPU farms — every jax buffer is a live array). Updates
    the per-device watermarks (flight events on crossings)."""
    try:
        import jax

        devices = jax.devices()
    except Exception:  # noqa: BLE001 - no backend in this process
        return []
    fallback_budget = default_budget_bytes()
    rows: List[dict] = []
    live_by_device: Optional[Dict[object, int]] = None
    for dev in devices:
        label = f"{getattr(dev, 'platform', '?')}:{getattr(dev, 'id', '?')}"
        stats = None
        ms = getattr(dev, "memory_stats", None)
        if ms is not None:
            try:
                stats = ms()
            except Exception:  # noqa: BLE001 - backend without stats
                stats = None
        if stats:
            in_use = int(stats.get("bytes_in_use", 0) or 0)
            budget = stats.get("bytes_limit") or fallback_budget
            source = "memory_stats"
        else:
            if live_by_device is None:
                live_by_device = _live_array_bytes()
            in_use = live_by_device.get(dev, 0)
            budget = fallback_budget
            source = "live_arrays"
        peak = _watermarks.update(label, in_use, budget, watermark)
        rows.append({
            "device": label,
            "bytes_in_use": in_use,
            "peak_bytes": peak,
            "budget_bytes": int(budget) if budget else None,
            "used_fraction": (in_use / budget) if budget else 0.0,
            "source": source,
        })
    return rows


def _live_array_bytes() -> Dict[object, int]:
    import jax

    out: Dict[object, int] = {}
    for arr in jax.live_arrays():
        try:
            devs = arr.devices()
        except Exception:  # noqa: BLE001 - deleted/donated mid-iteration
            continue
        nbytes = getattr(arr, "nbytes", 0) or 0
        for d in devs:
            # sharded arrays split evenly; single-device arrays whole
            out[d] = out.get(d, 0) + nbytes // max(1, len(devs))
    return out


def used_fraction() -> float:
    """Worst per-device used/budget fraction right now (0.0 when no
    budget is known) — the sample the ``memory``-kind SLO records."""
    rows = sample_devices()
    return max((r["used_fraction"] for r in rows), default=0.0)


def device_peaks() -> Dict[str, int]:
    return _watermarks.peaks()


class MemorySampler:
    """Background watermark sampler: one :func:`sample_devices` pass per
    ``interval_s`` while running. Started by :func:`start` (opt-in —
    scrapes also sample on demand); joined on stop."""

    def __init__(self, interval_s: float = 1.0,
                 watermark: float = DEFAULT_WATERMARK):
        self.interval_s = interval_s
        self.watermark = watermark
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MemorySampler":
        if self._thread is not None:
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="obs-memory-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                sample_devices(self.watermark)
            except Exception:  # noqa: BLE001 - sampler must outlive a
                # backend hiccup (device mid-reset)
                from ..utils.log import logger

                logger.exception("obs memory: device sample failed")


# ---------------------------------------------------------------------------
# queue / serving live accounting
# ---------------------------------------------------------------------------

_tracked_pipelines: "weakref.WeakSet" = weakref.WeakSet()
_tracked_serving: "weakref.WeakSet" = weakref.WeakSet()


def track_pipeline(pipeline) -> None:
    """Queue-occupancy accounting source (``Pipeline.play`` calls this;
    ``Pipeline.stop`` untracks so a dead pipeline's rows disappear from
    the scrape immediately, not at GC time)."""
    _tracked_pipelines.add(pipeline)
    if _san.LEAK:
        _san.note_acquire("memory_registration",
                          f"pipeline:{id(pipeline):x}", idempotent=True,
                          detail=getattr(pipeline, "name", ""))


def untrack_pipeline(pipeline) -> None:
    _tracked_pipelines.discard(pipeline)
    if _san.LEAK:
        _san.note_release("memory_registration", f"pipeline:{id(pipeline):x}")


def track_serving(source) -> None:
    """Register a serving byte source: anything with ``memory_bytes()``
    -> dict (the continuous LM engine's slot caches, guard-carrying
    schedulers). Weakly held — closed sources drop out."""
    _tracked_serving.add(source)


def untrack_serving(source) -> None:
    _tracked_serving.discard(source)


def queue_bytes(pipeline) -> Dict[str, dict]:
    """{queue-name: {depth, frame_bytes, bytes}} over one pipeline's
    queue elements — occupancy × negotiated frame size, read entirely
    from existing state (no hot-path hook)."""
    out: Dict[str, dict] = {}
    for el in getattr(pipeline, "elements", {}).values():
        if getattr(el, "ELEMENT_NAME", "") != "queue":
            continue
        caps = None
        for pad in el.sink_pads:
            if pad.caps is not None:
                caps = pad.caps
        frame = caps_frame_nbytes(caps)
        depth = el.stats.get("level", 0)
        out[el.name] = {"depth": depth, "frame_bytes": frame,
                        "bytes": depth * frame}
    return out


def serving_bytes() -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for src in list(_tracked_serving):
        try:
            snap = src.memory_bytes()
        except Exception:  # noqa: BLE001 - source mid-close
            continue
        name = snap.get("name", type(src).__name__)
        if name in out:
            name = f"{name}#{sum(1 for k in out if k.startswith(name))}"
        out[name] = snap
    return out


# ---------------------------------------------------------------------------
# admission guard (serving)
# ---------------------------------------------------------------------------

class AdmissionGuard:
    """Projected-bytes admission gate for the serving schedulers: every
    admitted request reserves its tensor bytes (× ``overhead`` for
    activations/padding) until completion; a reservation that would push
    the total past ``watermark × budget_bytes`` is refused and the
    scheduler sheds the request with a typed ``MemoryPressureError``
    BEFORE it can OOM a formed batch. Thread-safe; exposes its state to
    the memory snapshot via :func:`track_serving`."""

    def __init__(self, budget_bytes: int,
                 watermark: float = DEFAULT_WATERMARK,
                 overhead: float = 2.0, name: str = "guard"):
        if budget_bytes < 1:
            raise ValueError(f"budget_bytes={budget_bytes} must be >= 1")
        if not 0.0 < watermark <= 1.0:
            raise ValueError(f"watermark={watermark} must be in (0, 1]")
        self.budget_bytes = int(budget_bytes)
        self.watermark = watermark
        self.overhead = overhead
        self.name = name
        self._lock = named_lock(f"AdmissionGuard._lock:{name}")
        self._inflight = 0   # guarded-by: _lock
        self._peak = 0       # guarded-by: _lock
        self.shed = 0        # guarded-by: _lock
        track_serving(self)

    @property
    def limit_bytes(self) -> int:
        return int(self.watermark * self.budget_bytes)

    def reserve(self, nbytes: int) -> bool:   # pairs-with: release
        """Reserve ``nbytes × overhead``; False = would cross the
        watermark (caller sheds). Reservations above the limit in
        isolation are refused too — a single impossible request must
        not wedge admission."""
        need = int(nbytes * self.overhead)
        with self._lock:
            if self._inflight + need > self.limit_bytes:
                self.shed += 1
                return False
            self._inflight += need
            if self._inflight > self._peak:
                self._peak = self._inflight
        if _san.LEAK:
            _san.note_acquire("guard_reservation", self.name,
                              detail=f"{need} bytes")
        return True

    def release(self, nbytes: int) -> None:
        need = int(nbytes * self.overhead)
        with self._lock:
            self._inflight = max(0, self._inflight - need)
        if _san.LEAK:
            _san.note_release("guard_reservation", self.name)

    @property
    def inflight_bytes(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def peak_bytes(self) -> int:
        with self._lock:
            return self._peak

    def memory_bytes(self) -> dict:
        with self._lock:
            return {"name": f"guard:{self.name}", "kind": "admission_guard",
                    "bytes": self._inflight, "peak_bytes": self._peak,
                    "budget_bytes": self.budget_bytes,
                    "limit_bytes": self.limit_bytes, "shed": self.shed}


# ---------------------------------------------------------------------------
# module-level control (mirrors obs.profile: session OR calibration)
# ---------------------------------------------------------------------------

_ctl_lock = threading.Lock()
_started = False        # guarded-by: _ctl_lock — start()/stop() sessions
_calibrating = 0        # guarded-by: _ctl_lock — placement calibrations
_sampler: Optional[MemorySampler] = None


def _update_active() -> None:
    global ACTIVE
    ACTIVE = _started or _calibrating > 0


def start(sample_interval_s: float = 0.0) -> MemoryAccountant:
    """Switch memory accounting on: fused segments and filter opens
    record static estimates; ``sample_interval_s > 0`` also starts the
    background device-watermark sampler."""
    global _started, _sampler
    with _ctl_lock:
        _started = True
        _update_active()
        if sample_interval_s > 0 and _sampler is None:
            _sampler = MemorySampler(sample_interval_s)
            _sampler.start()
    return default_accountant


def stop() -> None:
    """Back to the one-global-check fast path (estimates are kept;
    ``reset()`` drops them). A calibration window still open keeps
    accounting alive until it closes."""
    global _started, _sampler
    with _ctl_lock:
        _started = False
        _update_active()
        sampler = _sampler
        _sampler = None
    if sampler is not None:
        sampler.stop()


def begin_calibration() -> None:   # pairs-with: end_calibration
    """Placement-calibration window (refcounted, paired with
    :func:`end_calibration`) — the planner needs byte estimates captured
    in the same window that measures stage latency."""
    global _calibrating
    with _ctl_lock:
        if _san.LEAK:
            _san.note_acquire("calibration", "obs.memory")
        _calibrating += 1
        _update_active()


def end_calibration() -> None:
    global _calibrating
    with _ctl_lock:
        if _san.LEAK:
            _san.note_release("calibration", "obs.memory")
        _calibrating = max(0, _calibrating - 1)
        _update_active()


def reset() -> None:
    default_accountant.reset()
    _watermarks.reset()


# ---------------------------------------------------------------------------
# snapshot + metrics collector + dashboard section
# ---------------------------------------------------------------------------

def snapshot() -> dict:
    """The ``GET /memory`` document: static stage estimates, model
    footprints, live device samples + watermarks, queue occupancy
    bytes, and serving byte sources."""
    queues: Dict[str, dict] = {}
    for pipe in list(_tracked_pipelines):
        qb = queue_bytes(pipe)
        if qb:
            queues[pipe.name] = qb
    return {
        "active": ACTIVE,
        "budget_bytes": default_budget_bytes(),
        "stages": default_accountant.stages(),
        "models": default_accountant.models(),
        "devices": sample_devices(),
        "queues": queues,
        "serving": serving_bytes(),
    }


_G_STAGE = obs_metrics.gauge(
    "nns_memory_stage_bytes",
    "static per-stage byte estimate (temp+output+argument+code+params)",
    ("stage", "field"))
_G_MODEL = obs_metrics.gauge(
    "nns_memory_model_params_bytes",
    "model parameter footprint (sum of leaf array nbytes)",
    ("model",))
_G_DEVICE = obs_metrics.gauge(
    "nns_memory_device_bytes", "live device buffer bytes", ("device",))
_G_DEVICE_PEAK = obs_metrics.gauge(
    "nns_memory_device_peak_bytes", "per-device high-water mark",
    ("device",))
_G_DEVICE_FRAC = obs_metrics.gauge(
    "nns_memory_device_used_fraction",
    "live bytes over the device budget (0 when no budget known)",
    ("device",))
_G_QUEUE = obs_metrics.gauge(
    "nns_memory_queue_bytes",
    "queue occupancy bytes (depth x negotiated frame size)",
    ("pipeline", "queue"))
_G_SERVING = obs_metrics.gauge(
    "nns_memory_serving_bytes",
    "serving-plane byte sources (KV caches, admission reservations)",
    ("source",))


def _collect_memory(_registry) -> None:
    for g in (_G_STAGE, _G_MODEL, _G_DEVICE, _G_DEVICE_PEAK,
              _G_DEVICE_FRAC, _G_QUEUE, _G_SERVING):
        g.clear()
    for name, cell in default_accountant.stages().items():
        _G_STAGE.set(cell.get("total_bytes", 0), stage=name, field="total")
        _G_STAGE.set(cell.get("param_bytes", 0), stage=name, field="params")
        _G_STAGE.set(cell.get("temp_bytes", 0), stage=name, field="temp")
    for name, nbytes in default_accountant.models().items():
        _G_MODEL.set(nbytes, model=name)
    for row in sample_devices():
        _G_DEVICE.set(row["bytes_in_use"], device=row["device"])
        _G_DEVICE_PEAK.set(row["peak_bytes"], device=row["device"])
        _G_DEVICE_FRAC.set(row["used_fraction"], device=row["device"])
    for pipe in list(_tracked_pipelines):
        for qname, q in queue_bytes(pipe).items():
            _G_QUEUE.set(q["bytes"], pipeline=pipe.name, queue=qname)
    for name, snap in serving_bytes().items():
        _G_SERVING.set(snap.get("bytes", 0), source=name)


obs_metrics.register_collector("memory", _collect_memory)


def _fmt_bytes(n: Optional[int]) -> str:
    if not n:
        return "0"
    v = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024.0 or unit == "GiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024.0
    return f"{v:.1f}GiB"


def render_section(mem_snap: dict) -> List[str]:
    """The MEMORY section of ``obs top`` (appended by
    ``profile.render_top`` when a memory snapshot is supplied)."""
    lines: List[str] = []
    devices = mem_snap.get("devices") or []
    if devices:
        lines.append("")
        lines.append("MEMORY (devices)")
        lines.append(f"  {'device':<12} {'in_use':>10} {'peak':>10} "
                     f"{'budget':>10} {'used':>6}")
        for d in devices:
            lines.append(
                f"  {d['device']:<12} {_fmt_bytes(d['bytes_in_use']):>10} "
                f"{_fmt_bytes(d['peak_bytes']):>10} "
                f"{_fmt_bytes(d.get('budget_bytes')):>10} "
                f"{d['used_fraction'] * 100:>5.1f}%")
    stages = mem_snap.get("stages") or {}
    if stages:
        lines.append("")
        lines.append("MEMORY (stage estimates)")
        lines.append(f"  {'stage':<40} {'total':>10} {'params':>10} "
                     f"{'temp':>10}")
        for name, cell in sorted(stages.items()):
            lines.append(
                f"  {name:<40} {_fmt_bytes(cell.get('total_bytes')):>10} "
                f"{_fmt_bytes(cell.get('param_bytes')):>10} "
                f"{_fmt_bytes(cell.get('temp_bytes')):>10}")
    queues = mem_snap.get("queues") or {}
    rows: List[Tuple[str, dict]] = [
        (f"{pipe}:{qname}", q)
        for pipe, qs in sorted(queues.items())
        for qname, q in sorted(qs.items())]
    if rows:
        lines.append("")
        lines.append("MEMORY (queues)")
        lines.append(f"  {'queue':<40} {'depth':>6} {'frame':>10} "
                     f"{'bytes':>10}")
        for name, q in rows:
            lines.append(f"  {name:<40} {q['depth']:>6d} "
                         f"{_fmt_bytes(q['frame_bytes']):>10} "
                         f"{_fmt_bytes(q['bytes']):>10}")
    serving = mem_snap.get("serving") or {}
    if serving:
        lines.append("")
        lines.append("MEMORY (serving)")
        for name, snap in sorted(serving.items()):
            row = f"  {name:<40} {_fmt_bytes(snap.get('bytes')):>10}"
            if "peak_bytes" in snap:
                row += f"  peak {_fmt_bytes(snap['peak_bytes'])}"
            if "pages_total" in snap:
                # paged-KV engines: occupancy answers "how close is the
                # pool to preempting", sharing answers "is prefix COW
                # earning its keep"
                total = snap["pages_total"] or 1
                row += (f"  pages {snap.get('pages_used', 0)}/"
                        f"{snap['pages_total']}"
                        f" ({snap.get('pages_used', 0) / total * 100:.0f}%)")
                if snap.get("pages_shared"):
                    row += f"  shared {snap['pages_shared']}"
            if "spec_acceptance_rate" in snap:
                row += f"  accept {snap['spec_acceptance_rate'] * 100:.0f}%"
            lines.append(row)
    return lines
