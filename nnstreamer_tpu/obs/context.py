"""Request-scoped tracing: trace contexts, spans, Perfetto export (L7).

One request through the full stack — ``QueryClient.request()`` → fabric
router (retries, hedges) → replica query server → serving batcher →
fused device segment — is ONE trace: a root span minted where the
request enters, child spans per attempt, and span *links* where
fan-in makes strict parentage a lie (a coalesced batch serves N
requests: the batch span links to every request span instead of
pretending one of them is its parent).

Wire propagation: a :class:`TraceContext` rides buffer meta as
``meta["trace"] = {"trace_id", "span_id"}`` — the query protocol's DATA
frames already carry meta as JSON (core/serialize.py), so the context
crosses every process boundary the tensors do, for free.

Cost discipline (the same contract as ``utils/trace.ACTIVE``): the hot
paths check ONE module-global, :data:`TRACING`, and do nothing else when
it is False. Spans use ``time.monotonic()`` so fabric/scheduler/fusion
timestamps (already monotonic) pass straight through.

Export: :func:`export_chrome_trace` writes chrome://tracing / Perfetto
JSON (``X`` complete events); trace_id/span_id/parent_span_id/links ride
each event's ``args`` so tooling (and tests) can reconstruct the tree.
Device XPlanes from ``utils.trace.jax_trace`` line up next to it.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis import sanitizer as _san
from . import flight

# module-global fast path: instrumented call sites check this and only
# this when tracing is off (the microbench overhead gate measures it)
TRACING = False

# per-process id prefix so traces from different processes (a remote
# replica, a subprocess service) can never collide
_uniq = f"{os.getpid():x}{int.from_bytes(os.urandom(3), 'big'):06x}"
_trace_seq = itertools.count(1)
_span_seq = itertools.count(1)

# finished spans, bounded (deque append/iteration is thread-safe under
# the GIL; oldest spans fall off — export is for recent activity, the
# flight recorder keeps the tail even when tracing is later disabled)
MAX_FINISHED = 16384
_finished: "collections.deque[Span]" = collections.deque(maxlen=MAX_FINISHED)
_finished_seq = itertools.count(1)
# the published total must never go BACKWARDS (Prometheus reads it as a
# counter; a regression renders as a reset → phantom rate spike), so the
# take-a-seq + publish pair is serialized by a tiny lock
_count_lock = threading.Lock()
_finished_total = 0                  # guarded-by: _count_lock (reads racy-ok)
_t0 = time.monotonic()


def _new_trace_id() -> str:
    return f"{_uniq}-{next(_trace_seq):x}"


def _new_span_id() -> str:
    return f"s{next(_span_seq):x}"


class TraceContext:
    """The propagatable half of a span: (trace_id, span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_meta(self) -> dict:
        """Wire form for ``buffer.meta['trace']`` (plain JSON-able dict)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @staticmethod
    def from_meta(obj) -> Optional["TraceContext"]:
        """Parse a wire/meta value back into a context; None for anything
        that is not one (meta is client-supplied data — never raise)."""
        if isinstance(obj, TraceContext):
            return obj
        if isinstance(obj, dict):
            t, s = obj.get("trace_id"), obj.get("span_id")
            if isinstance(t, str) and isinstance(s, str) and t and s:
                return TraceContext(t, s)
        return None

    def __repr__(self):
        return f"TraceContext({self.trace_id}/{self.span_id})"


class Span:
    """One timed operation inside a trace. Created via
    :func:`start_span` (live, call :meth:`end`) or :func:`record_span`
    (post-hoc, already finished)."""

    __slots__ = ("name", "kind", "trace_id", "span_id", "parent_id",
                 "start_s", "dur_s", "status", "attrs", "links", "tid",
                 "_done")

    def __init__(self, name: str, kind: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], start_s: float,
                 attrs: Optional[dict],
                 links: Sequence[Tuple[str, str]]):
        self.name = name
        self.kind = kind
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_s = start_s
        self.dur_s = 0.0
        self.status = "open"
        self.attrs = attrs or {}
        self.links: List[Tuple[str, str]] = list(links)
        self.tid = threading.get_ident()
        self._done = False

    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def add_link(self, ctx: Optional[TraceContext]) -> None:
        if ctx is not None:
            self.links.append((ctx.trace_id, ctx.span_id))

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def end(self, status: str = "ok") -> TraceContext:
        """Finish the span (idempotent) and record it."""
        if not self._done:
            self._done = True
            self.dur_s = max(0.0, time.monotonic() - self.start_s)
            self.status = status
            _record_finished(self)
        return self.context()

    def to_dict(self) -> dict:
        return {
            "name": self.name, "kind": self.kind,
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_span_id": self.parent_id,
            "start_s": self.start_s, "dur_s": self.dur_s,
            "status": self.status, "attrs": dict(self.attrs),
            "links": [{"trace_id": t, "span_id": s}
                      for t, s in self.links],
        }

    def __repr__(self):
        return (f"Span<{self.kind}:{self.name} {self.trace_id}/"
                f"{self.span_id} {self.status}>")


def _record_finished(span: Span) -> None:
    global _finished_total
    if _san.LEAK:
        # both terminal paths (Span.end and record_span's post-hoc
        # emission) funnel here: the span leaves the leak ledger
        _san.note_release("span", span.span_id)
    with _count_lock:
        _finished_total = next(_finished_seq)
    _finished.append(span)
    # spans land in the always-on flight recorder too, so a postmortem
    # dump shows the last requests even after tracing is switched off
    flight.record("span", f"{span.kind}:{span.name}",
                  {"trace": span.trace_id, "span": span.span_id,
                   "status": span.status,
                   "dur_ms": round(span.dur_s * 1e3, 3)})


def _coerce_parent(parent) -> Optional[TraceContext]:
    if parent is None:
        return None
    if isinstance(parent, Span):
        return parent.context()
    return TraceContext.from_meta(parent)


def start_span(name: str, kind: str = "span", parent=None,
               links: Sequence[TraceContext] = (),
               attrs: Optional[dict] = None,
               trace_id: Optional[str] = None) -> Span:
    """Open a live span. ``parent`` may be a :class:`TraceContext`, a
    :class:`Span`, or a meta dict; no parent (and no ``trace_id``) mints
    a fresh trace."""
    pctx = _coerce_parent(parent)
    tid = trace_id or (pctx.trace_id if pctx is not None
                       else _new_trace_id())
    span = Span(name, kind, tid, _new_span_id(),
                pctx.span_id if pctx is not None else None,
                time.monotonic(), attrs,
                [(c.trace_id, c.span_id) for c in links if c is not None])
    if _san.LEAK:
        _san.note_acquire("span", span.span_id,
                          detail=f"{kind}:{name}")
    return span


def record_span(name: str, kind: str = "span", parent=None,
                trace_id: Optional[str] = None,
                links: Sequence[TraceContext] = (),
                attrs: Optional[dict] = None,
                start_s: Optional[float] = None, dur_s: float = 0.0,
                status: str = "ok") -> TraceContext:
    """One-shot emission of an already-finished span (batch/fused
    dispatch paths measure first, report after). Returns the new span's
    context."""
    span = start_span(name, kind=kind, parent=parent, links=links,
                      attrs=attrs, trace_id=trace_id)
    if start_s is not None:
        span.start_s = start_s
    span._done = True
    span.dur_s = max(0.0, dur_s)
    span.status = status
    _record_finished(span)
    return span.context()


# -- control -----------------------------------------------------------------

def enable_tracing() -> None:
    global TRACING
    TRACING = True


def disable_tracing() -> None:
    global TRACING
    TRACING = False


def reset() -> None:
    """Drop recorded spans (tests / fresh export windows)."""
    _finished.clear()


def finished_spans() -> List[Span]:
    """Snapshot of the recent finished spans, oldest first."""
    return list(_finished)


def spans_for_trace(trace_id: str) -> List[Span]:
    return [s for s in _finished if s.trace_id == trace_id]


def mono_to_wall_offset() -> float:
    """``time.time() - time.monotonic()`` right now: the per-process
    clock offset that converts span start times (monotonic) to wall
    clock. Exported alongside spans so a DIFFERENT process (the fleet
    scraper) can place them on one shared timeline — monotonic epochs
    are process-private, wall clock is not."""
    return time.time() - time.monotonic()


def export_spans(trace_id: Optional[str] = None,
                 last: Optional[int] = None) -> dict:
    """Serializable span export for cross-process stitching (the
    ``GET /spans`` route — service/api.py). Each span dict additionally
    carries ``start_wall_s`` (wall-clock start, one offset applied to
    the whole batch) so the fleet view can interleave spans from many
    processes; ``pid`` identifies the exporting process in the joined
    Perfetto document."""
    offset = mono_to_wall_offset()
    spans = (spans_for_trace(trace_id) if trace_id is not None
             else finished_spans())
    if last is not None:
        spans = spans[-last:]
    out = []
    for s in spans:
        d = s.to_dict()
        d["start_wall_s"] = s.start_s + offset
        d["tid"] = s.tid
        out.append(d)
    return {"pid": os.getpid(), "tracing": TRACING,
            "mono_to_wall": offset, "spans": out}


def stats() -> dict:
    return {"finished_total": _finished_total, "retained": len(_finished),
            "tracing": TRACING}


# -- export ------------------------------------------------------------------

def export_chrome_trace(path: Optional[str] = None) -> dict:
    """Serialize the recent spans as chrome://tracing / Perfetto JSON.
    Returns the trace dict; also writes it to ``path`` when given. Each
    event's ``args`` carries trace_id / span_id / parent_span_id / links
    so the request tree survives the format."""
    events = []
    for s in finished_spans():
        events.append({
            "name": s.name,
            "cat": s.kind,
            "ph": "X",
            "ts": (s.start_s - _t0) * 1e6,
            "dur": s.dur_s * 1e6,
            "pid": os.getpid(),
            "tid": s.tid,
            "args": {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_span_id": s.parent_id,
                "status": s.status,
                "links": [{"trace_id": t, "span_id": sid}
                          for t, sid in s.links],
                **s.attrs,
            },
        })
    doc = {"traceEvents": events}
    if path:
        import json

        with open(path, "w") as fh:
            json.dump(doc, fh)
    return doc
