"""Continuous profiler: streaming-quantile attribution + artifacts (L7).

PR 7 gave the obs plane *signals* (spans, /metrics, the flight ring);
this module *interprets* them continuously: wall time attributed per
element, per fused device segment, and per queue-wait hop, aggregated
into mergeable streaming-quantile digests, and persisted as **profile
artifacts** keyed by (topology hash, caps, model version) — the input
the cross-device placement planner (ROADMAP item 1) and the AOT compile
cache (item 5) consume. Profiled model segmentation is the lever the
multi-TPU paper shows dominating inference time (arxiv 2503.01025);
NNShark motivates exactly this per-element stream profiling for
on-device AI (arxiv 1901.04985).

Four attribution channels, all riding hooks that already exist:

* **elements** — a :class:`Tracer` installed by :func:`start` receives
  the per-hop elapsed time ``Pad.push`` already measures when tracing is
  active (``utils/trace.notify_flow``); nothing new on the pad path.
* **fused segments** — ``FusedSegment.dispatch`` feeds its host dispatch
  time per buffer and its sampled device-complete probe (the existing
  every-16-dispatches sync) into ``fused`` / ``fused_device`` series.
* **queue waits** — ``QueueElement`` stamps entry time and measures the
  wait at the worker pop (plus instantaneous depth), gated on one module
  global.
* **requests** — the serving scheduler and the fabric router record
  end-to-end request latency + outcome into *windowed* series
  (:class:`WindowedSeries`), the substrate the SLO engine
  (:mod:`.slo`) evaluates burn rates from.

Cost contract (same as tracing, gated by tools/microbench_overhead.py):
with profiling off every hook is ONE module-global check
(:data:`ACTIVE`); enabled overhead is reported, not gated — turning the
profiler on is a deliberate trade, and the per-sample cost is two
timestamps plus one log-bucket insert.

Surfaces: ``python -m nnstreamer_tpu obs profile|top``, ``GET /profile``
on the control plane, ``nns_profile_*`` histograms at ``GET /metrics``.
See docs/observability.md (Profiling section) for the artifact schema
and digest error bounds.
"""
from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..analysis import sanitizer as _san
from ..analysis.sanitizer import named_lock
from . import metrics as obs_metrics

# module-global fast path: queue/fusion/serving/fabric hooks check this
# and only this when profiling is off (the microbench gate measures it)
ACTIVE = False


class QuantileDigest:
    """Mergeable streaming-quantile sketch: fixed-γ log buckets (the
    DDSketch construction) over positive values, stdlib-only.

    Accuracy guarantee (documented, tested): with relative accuracy
    ``alpha`` every bucket ``i`` covers ``(γ^(i-1), γ^i]`` for
    ``γ = (1+α)/(1-α)``, and the mid-bucket estimate ``2γ^i/(γ+1)`` is
    within ``α`` *relative* error of any value in the bucket — so any
    quantile estimate is within ``α·v`` of the exact sample quantile
    ``v`` (values at or below :data:`MIN_VALUE` collapse into a zero
    bucket and report 0.0).

    Merging is EXACT: two digests with the same ``alpha`` share bucket
    boundaries, so ``a.merge(b)`` is bucket-wise addition and equals the
    digest of the pooled samples bit-for-bit — replica digests aggregate
    without accuracy loss, the property profile artifacts and the SLO
    engine rely on.
    """

    __slots__ = ("alpha", "_gamma", "_lg", "_buckets", "_zero",
                 "count", "sum", "min", "max")

    MIN_VALUE = 1e-9  # seconds; below this resolution nothing is timed

    def __init__(self, alpha: float = 0.01):
        if not 0.0 < alpha < 0.5:
            raise ValueError(f"alpha={alpha} must be in (0, 0.5)")
        self.alpha = alpha
        self._gamma = (1.0 + alpha) / (1.0 - alpha)
        self._lg = math.log(self._gamma)
        self._buckets: Dict[int, int] = {}
        self._zero = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def add(self, value: float, n: int = 1) -> None:
        v = float(value)
        if v < 0.0:
            v = 0.0  # durations; clock skew must not poison the sketch
        self.count += n
        self.sum += v * n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v <= self.MIN_VALUE:
            self._zero += n
            return
        i = math.ceil(math.log(v) / self._lg)
        b = self._buckets
        b[i] = b.get(i, 0) + n

    def _bucket_value(self, i: int) -> float:
        return 2.0 * self._gamma ** i / (self._gamma + 1.0)

    def quantile(self, q: float) -> float:
        """The q-quantile estimate (q in [0, 1]); 0.0 on an empty digest."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q={q} must be in [0, 1]")
        if self.count == 0:
            return 0.0
        rank = q * (self.count - 1)
        if rank < self._zero:
            return 0.0
        acc = self._zero
        for i in sorted(self._buckets):
            acc += self._buckets[i]
            if rank < acc:
                # clamp to the observed extremes: the edge buckets'
                # midpoints can only move INTO the α bound, never out
                return min(max(self._bucket_value(i), self.min), self.max)
        return self.max

    def count_above(self, threshold: float) -> int:
        """Samples greater than ``threshold`` — the SLO engine's "bad
        event" count. Exact up to the bucket holding the threshold
        (boundary error bounded by the same α)."""
        if self.count == 0:
            return 0
        if threshold <= self.MIN_VALUE:
            return self.count - self._zero
        k = math.ceil(math.log(threshold) / self._lg)
        return sum(c for i, c in self._buckets.items() if i > k)

    def merge(self, other: "QuantileDigest") -> "QuantileDigest":
        """Fold ``other`` into this digest (in place; returns self)."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge digests with alpha {self.alpha} != "
                f"{other.alpha} (bucket boundaries differ)")
        self.count += other.count
        self.sum += other.sum
        self._zero += other._zero
        if other.count:
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)
        b = self._buckets
        for i, c in other._buckets.items():
            b[i] = b.get(i, 0) + c
        return self

    def copy(self) -> "QuantileDigest":
        d = QuantileDigest(self.alpha)
        d.merge(self)
        return d

    def to_dict(self) -> dict:
        return {
            "alpha": self.alpha,
            "count": self.count,
            "sum": self.sum,
            "zero": self._zero,
            "min": None if self.count == 0 else self.min,
            "max": None if self.count == 0 else self.max,
            "buckets": {str(i): c for i, c in sorted(self._buckets.items())},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileDigest":
        dig = cls(float(d["alpha"]))
        dig.count = int(d["count"])
        dig.sum = float(d["sum"])
        dig._zero = int(d["zero"])
        if d.get("min") is not None:
            dig.min = float(d["min"])
        if d.get("max") is not None:
            dig.max = float(d["max"])
        dig._buckets = {int(i): int(c) for i, c in d["buckets"].items()}
        return dig

    def __eq__(self, other) -> bool:
        """Sketch equality: same alpha, counts, and bucket contents —
        every quantile answer is identical. ``sum`` is deliberately
        excluded (float accumulation order differs between a merged and
        a pooled digest by ULPs)."""
        return (isinstance(other, QuantileDigest)
                and abs(self.alpha - other.alpha) < 1e-12
                and self.count == other.count
                and self._zero == other._zero
                and self._buckets == other._buckets
                and (self.count == 0
                     or (self.min == other.min and self.max == other.max)))

    def __repr__(self):
        return (f"QuantileDigest<n={self.count} p50="
                f"{self.quantile(0.5) * 1e3:.3f}ms "
                f"p99={self.quantile(0.99) * 1e3:.3f}ms>")


class WindowedSeries:
    """Request series bucketed into per-``resolution_s`` cells, each a
    (digest, ok, err) triple, on a ring covering ``horizon_s`` seconds.
    ``window(seconds)`` merges the trailing cells — because digest merge
    is exact, a 300-second window IS the digest of every sample in it.
    One series per (scheduler | pool | availability target); the SLO
    engine's multi-window burn rates and ``GET /profile`` read the same
    cells."""

    def __init__(self, alpha: float = 0.01, horizon_s: float = 900.0,
                 resolution_s: float = 1.0):
        if resolution_s <= 0:
            raise ValueError(f"resolution_s={resolution_s} must be > 0")
        self.alpha = alpha
        self.resolution_s = float(resolution_s)
        self._n = max(2, int(math.ceil(horizon_s / resolution_s)) + 1)
        # each slot: [epoch, digest, ok, err] — slot reuse is detected by
        # the stored epoch, so the ring never needs a sweeper
        self._cells: List[Optional[list]] = [None] * self._n
        self._lock = threading.Lock()
        self.total = QuantileDigest(alpha)     # guarded-by: _lock
        self.errors = 0                        # guarded-by: _lock

    def observe(self, value_s: float, ok: bool = True,
                now: Optional[float] = None) -> None:
        t = time.monotonic() if now is None else now
        epoch = int(t / self.resolution_s)
        idx = epoch % self._n
        with self._lock:
            cell = self._cells[idx]
            if cell is None or cell[0] != epoch:
                cell = self._cells[idx] = [epoch, QuantileDigest(self.alpha),
                                           0, 0]
            cell[1].add(value_s)
            if ok:
                cell[2] += 1
            else:
                cell[3] += 1
                self.errors += 1
            self.total.add(value_s)

    def window(self, seconds: float, now: Optional[float] = None
               ) -> Tuple[QuantileDigest, int, int]:
        """(merged digest, ok count, err count) over the trailing
        ``seconds`` (including the current partial cell)."""
        t = time.monotonic() if now is None else now
        hi = int(t / self.resolution_s)
        lo = hi - max(1, int(math.ceil(seconds / self.resolution_s))) + 1
        merged = QuantileDigest(self.alpha)
        ok = err = 0
        with self._lock:
            for cell in self._cells:
                if cell is not None and lo <= cell[0] <= hi:
                    merged.merge(cell[1])
                    ok += cell[2]
                    err += cell[3]
        return merged, ok, err

    def snapshot(self) -> dict:
        with self._lock:
            dig = self.total.copy()
            errors = self.errors
        return {
            "count": dig.count,
            "errors": errors,
            "p50_ms": dig.quantile(0.5) * 1e3,
            "p99_ms": dig.quantile(0.99) * 1e3,
            "max_ms": (dig.max if dig.count else 0.0) * 1e3,
        }

    def export_state(self) -> dict:
        """Raw serializable form for cross-process aggregation (the
        ``GET /profile?raw=1`` route the fleet scraper reads): every
        live cell's digest + ok/err counts plus the cumulative total
        digest. Because digest merge is exact, a consumer that merges
        these cells gets bit-for-bit the digest of the pooled samples —
        the fleet p99 IS the pooled p99 (obs/fleet.py)."""
        with self._lock:
            cells = [{"epoch": c[0], "digest": c[1].to_dict(),
                      "ok": c[2], "err": c[3]}
                     for c in self._cells if c is not None]
            total = self.total.to_dict()
            errors = self.errors
        return {"alpha": self.alpha, "resolution_s": self.resolution_s,
                "cells": cells, "total": total, "errors": errors}


class _Series:
    """One duration-attribution channel: cumulative digest + rate anchors."""

    __slots__ = ("count", "total_s", "digest", "first_t", "last_t", "depth")

    def __init__(self, alpha: float):
        self.count = 0
        self.total_s = 0.0
        self.digest = QuantileDigest(alpha)
        self.first_t: Optional[float] = None
        self.last_t = 0.0
        self.depth: Optional[int] = None  # queues: level at last pop

    def snapshot(self) -> dict:
        d = self.digest
        span = (self.last_t - self.first_t) if self.first_t else 0.0
        out = {
            "count": self.count,
            "total_s": self.total_s,
            "rate_hz": (self.count - 1) / span if span > 0 else 0.0,
            "p50_ms": d.quantile(0.5) * 1e3,
            "p90_ms": d.quantile(0.9) * 1e3,
            "p99_ms": d.quantile(0.99) * 1e3,
            "max_ms": (d.max if d.count else 0.0) * 1e3,
        }
        if self.depth is not None:
            out["depth"] = self.depth
        return out


# the new profiler histograms publish into the metrics plane with the
# SLO-aligned bucket presets (docs/observability.md#histogram-buckets)
_STAGE_HIST = obs_metrics.histogram(
    "nns_profile_stage_seconds",
    "profiled stage duration (element hop / fused dispatch / queue wait)",
    ("scope", "stage"),
    buckets=obs_metrics.Histogram.LATENCY_BUCKETS_STAGE)
_REQUEST_HIST = obs_metrics.histogram(
    "nns_profile_request_seconds",
    "profiled end-to-end request latency per series",
    ("series",),
    buckets=obs_metrics.Histogram.LATENCY_BUCKETS_REQUEST)


class Profiler:
    """The process-wide attribution store. Duration scopes: ``element``
    (per pad hop, via the tracer), ``fused`` / ``fused_device`` (host
    dispatch / sampled device-complete, from FusedSegment), ``queue_wait``
    (queue entry → worker pop), ``serving`` (batch/step events). Names
    are ``<pipeline>:<element-or-segment>`` so artifacts can be captured
    per pipeline and merged across replicas."""

    def __init__(self, alpha: float = 0.01, horizon_s: float = 900.0):
        self.alpha = alpha
        self.horizon_s = horizon_s
        self._lock = named_lock("Profiler._lock")
        self._durations: Dict[Tuple[str, str], _Series] = {}  # guarded-by: _lock
        self._requests: Dict[str, WindowedSeries] = {}        # guarded-by: _lock

    # -- recording (hot when profiling is on) --------------------------------
    def observe(self, scope: str, name: str, seconds: float,
                depth: Optional[int] = None) -> None:
        now = time.monotonic()
        key = (scope, name)
        with self._lock:
            s = self._durations.get(key)
            if s is None:
                s = self._durations[key] = _Series(self.alpha)
            s.count += 1
            s.total_s += seconds
            s.digest.add(seconds)
            if s.first_t is None:
                s.first_t = now
            s.last_t = now
            if depth is not None:
                s.depth = depth
        _STAGE_HIST.observe(seconds, scope=scope, stage=name)

    def record_request(self, series: str, seconds: float, ok: bool = True,
                       now: Optional[float] = None) -> None:
        with self._lock:
            ws = self._requests.get(series)
            if ws is None:
                ws = self._requests[series] = WindowedSeries(
                    self.alpha, self.horizon_s)
        ws.observe(seconds, ok=ok, now=now)
        _REQUEST_HIST.observe(seconds, series=series)

    # -- reading -------------------------------------------------------------
    def series(self, scope: str, name: str) -> Optional[_Series]:
        with self._lock:
            return self._durations.get((scope, name))

    def request_series(self, series: str) -> Optional[WindowedSeries]:
        with self._lock:
            return self._requests.get(series)

    def request_window(self, series: str, seconds: float,
                       now: Optional[float] = None
                       ) -> Tuple[QuantileDigest, int, int]:
        ws = self.request_series(series)
        if ws is None:
            return QuantileDigest(self.alpha), 0, 0
        return ws.window(seconds, now=now)

    def snapshot(self) -> dict:
        """JSON-friendly view of every series (``GET /profile``). The
        duration rows are rendered UNDER the lock: quantile() iterates
        the live bucket dict, and a concurrent ``observe`` inserting a
        new bucket would otherwise blow the iteration up mid-scrape."""
        out: Dict[str, dict] = {}
        with self._lock:
            for (scope, name), s in sorted(self._durations.items()):
                out.setdefault(scope, {})[name] = s.snapshot()
            requests = dict(self._requests)
        return {
            "active": ACTIVE,
            "durations": out,
            # WindowedSeries.snapshot() locks per series internally
            "requests": {name: ws.snapshot()
                         for name, ws in sorted(requests.items())},
        }

    def export_state(self) -> dict:
        """Raw serializable export of every series (the fleet-scrape
        contract — docs/observability.md#fleet): duration digests as
        their bucket dicts and request series as windowed cells, plus
        the process's monotonic→wall clock offset so a scraper in
        ANOTHER process can align the cell epochs onto wall time.
        Everything is copied under the profiler lock (digest bucket
        dicts mutate under concurrent ``observe``)."""
        durations: Dict[str, dict] = {}
        with self._lock:
            for (scope, name), s in sorted(self._durations.items()):
                durations.setdefault(scope, {})[name] = {
                    "count": s.count,
                    "total_s": s.total_s,
                    "digest": s.digest.to_dict(),
                }
            requests = dict(self._requests)
        from . import context as obs_context

        return {
            "mono_to_wall": obs_context.mono_to_wall_offset(),
            "alpha": self.alpha,
            "durations": durations,
            # WindowedSeries.export_state locks per series internally
            "requests": {name: ws.export_state()
                         for name, ws in sorted(requests.items())},
        }

    def reset(self) -> None:
        with self._lock:
            self._durations.clear()
            self._requests.clear()


# -- canonical series naming --------------------------------------------------

def canonical_base(el) -> str:
    """The element's stable profile name: its own name when explicitly
    set, else a positional alias ``<type>@<index-in-pipeline>`` — the
    auto-generated name embeds a process-global instance counter, so a
    supervised restart or a sibling replica parsing the same launch line
    would get DIFFERENT names (and artifact keys/entries would never
    line up across the runs they are meant to merge over)."""
    if getattr(el, "auto_named", False):
        pipe = getattr(el, "pipeline", None)
        if pipe is not None:
            try:
                idx = list(pipe.elements).index(el.name)
            except ValueError:
                idx = -1
            return f"{el.ELEMENT_NAME}@{idx}"
    return el.name


def series_name(el) -> str:
    """``<pipeline>:<canonical-base>`` — cached on the element (the
    tracer/queue hot paths pay one attribute read after the first hit)."""
    cached = el.__dict__.get("_prof_series")
    if cached is None:
        pipe = getattr(el, "pipeline", None)
        cached = (f"{pipe.name if pipe is not None else '?'}:"
                  f"{canonical_base(el)}")
        el.__dict__["_prof_series"] = cached
    return cached


class _ProfilerTracer:
    """The element-attribution half: a ``utils.trace.Tracer`` receiving
    the per-hop elapsed time ``Pad.push`` already measures when any
    tracer is installed. Fused dispatches are recorded directly by
    ``FusedSegment.dispatch`` (with their pipeline prefix), so the
    ``fused``-kind serving events are skipped here."""

    NAME = "profiler"

    def __init__(self, profiler: Profiler):
        self._p = profiler

    def buffer_flow(self, pad, buf, elapsed_s: float) -> None:
        peer = pad.peer
        if peer is None:
            return
        self._p.observe("element", series_name(peer.element), elapsed_s)

    def serving_event(self, kind: str, name: str, start_s: float,
                      dur_s: float, meta: dict) -> None:
        if kind == "fused":
            return  # recorded at the dispatch site with pipeline prefix
        self._p.observe("serving", f"{kind}:{name}", dur_s)

    def results(self) -> dict:
        return self._p.snapshot()


# -- module-level control (the API hot call sites use) -----------------------

default_profiler = Profiler()
_ctl_lock = threading.Lock()
_tracer: Optional[_ProfilerTracer] = None
# ACTIVE is the OR of three independent halves, so an explicit
# start()/stop() profiling session, a running SLO engine
# (enable_recording/disable_recording), and a placement-calibration
# window (begin_calibration/end_calibration, refcounted — several
# pipelines may calibrate concurrently) cannot starve each other:
# stop() ending a capture while an engine is alive must NOT silence the
# request series its burn rates are computed from, and a calibration
# finishing must not switch off another pipeline's window
_started = False        # guarded-by: _ctl_lock — start()/stop() sessions
_recording = False      # guarded-by: _ctl_lock — SLO-engine recording
_calibrating = 0        # guarded-by: _ctl_lock — placement calibrations


def profiler() -> Profiler:
    return default_profiler


def _update_active() -> None:
    global ACTIVE
    ACTIVE = _started or _recording or _calibrating > 0


def start(elements: bool = True) -> Profiler:
    """Switch continuous profiling on. ``elements=True`` (default) also
    installs the pad-hop tracer for per-element attribution; queue-wait,
    fused-segment, and request recording activate either way."""
    global _started, _tracer
    from ..utils import trace

    with _ctl_lock:
        if elements and _tracer is None:
            _tracer = _ProfilerTracer(default_profiler)
            trace.install_tracer(_tracer)
        _started = True
        _update_active()
    return default_profiler


def enable_recording() -> None:   # pairs-with: disable_recording
    """Queue/fused/request recording WITHOUT the per-hop element tracer —
    what the SLO engine needs. Independent of start()/stop(): a capture
    session ending does not switch a running engine's series off."""
    global _recording
    with _ctl_lock:
        if _san.LEAK and not _recording:
            # boolean half: ledger one unit per on→off transition
            _san.note_acquire("recording", "obs.profile")
        _recording = True
        _update_active()


def disable_recording() -> None:
    """The engine half's off switch (the last stopping SloEngine calls
    this)."""
    global _recording
    with _ctl_lock:
        if _san.LEAK and _recording:
            _san.note_release("recording", "obs.profile")
        _recording = False
        _update_active()


def begin_calibration() -> None:   # pairs-with: end_calibration
    """Placement-calibration recording (queue/fused hooks, no element
    tracer), REFCOUNTED: each ``begin`` must be paired with one ``end``,
    and concurrent calibrating pipelines keep recording alive until the
    last one finishes (runtime/placement.py)."""
    global _calibrating
    with _ctl_lock:
        if _san.LEAK:
            _san.note_acquire("calibration", "obs.profile")
        _calibrating += 1
        _update_active()


def end_calibration() -> None:
    global _calibrating
    with _ctl_lock:
        if _san.LEAK:
            _san.note_release("calibration", "obs.profile")
        _calibrating = max(0, _calibrating - 1)
        _update_active()


def stop() -> None:
    """End a start() session: back to the one-global-check fast path
    unless an SLO engine still records (data is kept; reset() drops it)."""
    global _started, _tracer
    from ..utils import trace

    with _ctl_lock:
        _started = False
        _update_active()
        if _tracer is not None:
            trace.uninstall_tracer(_tracer)
            _tracer = None


def reset() -> None:
    default_profiler.reset()


def snapshot() -> dict:
    snap = default_profiler.snapshot()
    # NNS_XFERCHECK byte ledger: when the transfer sanitizer is armed,
    # per-(stage,direction) transfer bytes ride the same snapshot that
    # feeds GET /profile and `obs top` — one surface for "where do my
    # bytes cross the host/device (and process) boundary"
    if _san.XFER:
        snap["transfers"] = _san.xfer_transfers()
    return snap


def export_state() -> dict:
    """Raw digest export of the default profiler (the fleet-scrape
    contract; ``GET /profile?raw=1``)."""
    return default_profiler.export_state()


# hot call sites (queue pop, fused dispatch, request completion) — each
# caller checks ACTIVE first, so these run only while profiling
def record_queue_wait(name: str, wait_s: float, depth: int) -> None:
    default_profiler.observe("queue_wait", name, wait_s, depth=depth)


def record_fused(name: str, host_s: float,
                 device_s: Optional[float] = None) -> None:
    default_profiler.observe("fused", name, host_s)
    if device_s is not None:
        default_profiler.observe("fused_device", name, device_s)


def record_request(series: str, seconds: float, ok: bool = True) -> None:
    default_profiler.record_request(series, seconds, ok=ok)


# -- profile artifacts -------------------------------------------------------

SCHEMA_VERSION = 1
# duration scopes that belong to a pipeline (name-prefixed) and persist
# into artifacts; request/serving series are deployment-shaped, not
# topology-shaped, and stay out
_ARTIFACT_SCOPES = ("element", "fused", "fused_device", "queue_wait")


def topology_hash(pipeline) -> str:
    """Stable hash of a pipeline's topology: canonical element names
    (positional aliases for auto-named elements — see
    :func:`canonical_base`), element types, and the pad link graph (NOT
    runtime state) — the artifact/AOT-cache key half that survives
    restarts and identifies 'the same graph' across processes and
    replicas parsing the same launch line."""
    canon = {name: canonical_base(el)
             for name, el in pipeline.elements.items()}
    items: List[str] = []
    for name in sorted(pipeline.elements, key=lambda n: canon[n]):
        el = pipeline.elements[name]
        items.append(f"{canon[name]}={el.ELEMENT_NAME}")
        for pad in el.src_pads:
            if pad.peer is not None:
                items.append(f"{canon[name]}.{pad.name}->"
                             f"{canon[pad.peer.element.name]}."
                             f"{pad.peer.name}")
    return hashlib.sha256("\n".join(items).encode()).hexdigest()[:16]


def _negotiated_caps(pipeline) -> str:
    for sink in pipeline.sinks:
        for pad in sink.sink_pads:
            if pad.caps is not None:
                return str(pad.caps)
    return ""


class ProfileArtifact:
    """A persisted profile: per-entry digests keyed by
    (topology hash, caps, model version). ``load``/``merge``/``diff``
    are the APIs the placement planner and AOT cache consume — replicas
    of the same topology merge exactly (digest merge is lossless).

    The ``memory`` section (PR 10, :mod:`.memory`) carries per-stage
    static byte estimates under the SAME stage keys the duration scopes
    use; its merge semantics are **max-watermark** per field — a
    footprint is a high-water mark, so merged replicas report the worst
    observed footprint, never a sum.

    The ``quality`` section (PR 11, :mod:`.quality`) carries per-edge
    tensor-health cells (NaN/Inf/zero counts, moments, a log-bucket
    value sketch) under the same keys; its merge is **additive** with
    exact histogram merge — a health sketch is a sample population.
    Artifacts with a quality section are the baselines
    ``quality.set_baseline`` scores live drift against."""

    def __init__(self, key: dict, entries: Dict[str, Dict[str, dict]],
                 pipeline: str = "", created: Optional[float] = None,
                 memory: Optional[Dict[str, dict]] = None,
                 quality: Optional[Dict[str, dict]] = None):
        self.key = {"topology": str(key.get("topology", "")),
                    "caps": str(key.get("caps", "")),
                    "model_version": str(key.get("model_version", ""))}
        # entries: {scope: {name: {"count": int, "total_s": float,
        #                          "digest": QuantileDigest}}}
        self.entries = entries
        # memory: {stage: {"kind": str, <byte fields>, "total_bytes": int}}
        self.memory: Dict[str, dict] = dict(memory or {})
        # quality: {stage: TensorHealth cell — obs/quality.py to_cell()}
        self.quality: Dict[str, dict] = dict(quality or {})
        self.pipeline = pipeline
        self.created = time.time() if created is None else created

    # -- construction --------------------------------------------------------
    @classmethod
    def capture(cls, pipeline, caps: Optional[str] = None,
                model_version: str = "",
                profiler: Optional[Profiler] = None) -> "ProfileArtifact":
        """Extract ``pipeline``'s series from the (default) profiler,
        stripping the pipeline-name prefix so artifacts captured on
        different replicas of the same topology merge by entry name."""
        p = profiler if profiler is not None else default_profiler
        prefix = f"{pipeline.name}:"
        entries: Dict[str, Dict[str, dict]] = {}
        # digests are copied UNDER the profiler lock — a concurrent
        # observe() inserting a bucket must not race the copy's iteration
        with p._lock:
            for (scope, name), s in p._durations.items():
                if (scope not in _ARTIFACT_SCOPES
                        or not name.startswith(prefix)):
                    continue
                entries.setdefault(scope, {})[name[len(prefix):]] = {
                    "count": s.count,
                    "total_s": s.total_s,
                    "digest": s.digest.copy(),
                }
        # byte estimates ride the same key: the memory accountant names
        # stages exactly like the profiler series, so the prefix strip
        # lines fused/filter footprints up with the duration entries
        from . import memory as obs_memory

        mem = {name[len(prefix):]: cell
               for name, cell in obs_memory.accountant()
               .stages(prefix).items()}
        # tensor-health cells ride the same key + prefix strip, so a
        # captured artifact doubles as a drift baseline
        from . import quality as obs_quality

        qual = {name[len(prefix):]: cell
                for name, cell in obs_quality.accountant()
                .stages(prefix).items()}
        return cls(
            {"topology": topology_hash(pipeline),
             "caps": _negotiated_caps(pipeline) if caps is None else caps,
             "model_version": model_version},
            entries, pipeline=pipeline.name, memory=mem, quality=qual)

    # -- persistence ---------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "schema": SCHEMA_VERSION,
            "kind": "nns-profile",
            "created": self.created,
            "pipeline": self.pipeline,
            "key": dict(self.key),
            "entries": {
                scope: {name: {"count": e["count"],
                               "total_s": e["total_s"],
                               "digest": e["digest"].to_dict()}
                        for name, e in sorted(names.items())}
                for scope, names in sorted(self.entries.items())
            },
            "memory": {name: dict(cell)
                       for name, cell in sorted(self.memory.items())},
            "quality": {name: dict(cell)
                        for name, cell in sorted(self.quality.items())},
        }

    def save(self, path: str) -> str:
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh, indent=2)
        return path

    @classmethod
    def from_dict(cls, d: dict) -> "ProfileArtifact":
        if d.get("kind") != "nns-profile":
            raise ValueError("not a profile artifact (kind != nns-profile)")
        if int(d.get("schema", 0)) > SCHEMA_VERSION:
            raise ValueError(
                f"artifact schema {d['schema']} is newer than supported "
                f"{SCHEMA_VERSION}")
        entries = {
            scope: {name: {"count": int(e["count"]),
                           "total_s": float(e["total_s"]),
                           "digest": QuantileDigest.from_dict(e["digest"])}
                    for name, e in names.items()}
            for scope, names in d.get("entries", {}).items()
        }
        return cls(d["key"], entries, pipeline=d.get("pipeline", ""),
                   created=d.get("created"),
                   memory={str(n): dict(c)
                           for n, c in (d.get("memory") or {}).items()},
                   quality={str(n): dict(c)
                            for n, c in (d.get("quality") or {}).items()})

    @classmethod
    def load(cls, path: str) -> "ProfileArtifact":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    # -- algebra -------------------------------------------------------------
    def merge(self, other: "ProfileArtifact") -> "ProfileArtifact":
        """Fold another run/replica of the SAME key into this artifact
        (in place; returns self). Digest merge is exact, so merged
        replica profiles equal the pooled-sample profile."""
        if other.key != self.key:
            raise ValueError(
                f"cannot merge artifacts with different keys: "
                f"{self.key} != {other.key}")
        for scope, names in other.entries.items():
            mine = self.entries.setdefault(scope, {})
            for name, e in names.items():
                cell = mine.get(name)
                if cell is None:
                    mine[name] = {"count": e["count"],
                                  "total_s": e["total_s"],
                                  "digest": e["digest"].copy()}
                else:
                    cell["count"] += e["count"]
                    cell["total_s"] += e["total_s"]
                    cell["digest"].merge(e["digest"])
        # memory is max-watermark per field: two replicas' footprints
        # merge to the worst observed, never a sum. total_bytes is then
        # RECOMPUTED from the merged field maxes — maxing it
        # independently would understate a cell whose replicas peaked on
        # different fields (and the planner reads total_bytes)
        from . import memory as obs_memory

        for name, cell in other.memory.items():
            mine = self.memory.get(name)
            if mine is None:
                self.memory[name] = dict(cell)
                continue
            for field, value in cell.items():
                if field == "kind":
                    mine.setdefault("kind", value)
                elif isinstance(value, (int, float)):
                    if value > mine.get(field, 0):
                        mine[field] = value
            if any(f in mine for f in obs_memory.FIELDS):
                mine["total_bytes"] = sum(int(mine.get(f, 0) or 0)
                                          for f in obs_memory.FIELDS)
        # quality is additive: counts sum and the value sketches merge
        # exactly (obs/quality.py merge_cells) — two replicas' health
        # cells pool into the health of the pooled samples
        from . import quality as obs_quality

        for name, cell in other.quality.items():
            mine = self.quality.get(name)
            if mine is None:
                self.quality[name] = dict(cell)
            else:
                obs_quality.merge_cells(mine, cell)
        self.created = max(self.created, other.created)
        return self

    def diff(self, other: "ProfileArtifact") -> dict:
        """Per-entry p50/p99 deltas (other - self), for regression hunts
        across model versions / code changes. Keys need not match —
        entries are compared by (scope, name); one-sided entries report
        the side they exist on."""
        out: Dict[str, dict] = {}
        scopes = set(self.entries) | set(other.entries)
        for scope in sorted(scopes):
            a_names = self.entries.get(scope, {})
            b_names = other.entries.get(scope, {})
            for name in sorted(set(a_names) | set(b_names)):
                a, b = a_names.get(name), b_names.get(name)
                row: dict = {"scope": scope}
                if a is not None:
                    row["a"] = {"count": a["count"],
                                "p50_ms": a["digest"].quantile(0.5) * 1e3,
                                "p99_ms": a["digest"].quantile(0.99) * 1e3}
                if b is not None:
                    row["b"] = {"count": b["count"],
                                "p50_ms": b["digest"].quantile(0.5) * 1e3,
                                "p99_ms": b["digest"].quantile(0.99) * 1e3}
                if a is not None and b is not None:
                    row["delta_p50_ms"] = (row["b"]["p50_ms"]
                                           - row["a"]["p50_ms"])
                    row["delta_p99_ms"] = (row["b"]["p99_ms"]
                                           - row["a"]["p99_ms"])
                out.setdefault(scope, {})[name] = row
        return out

    def summary(self) -> dict:
        """{scope: {name: {count, p50_ms, p99_ms, total_s}}} — the
        human/bench-facing attribution table (plus the ``memory``
        byte-estimate section when captured)."""
        out = {
            scope: {name: {"count": e["count"],
                           "total_s": round(e["total_s"], 6),
                           "p50_ms": round(e["digest"].quantile(0.5) * 1e3, 4),
                           "p99_ms": round(e["digest"].quantile(0.99) * 1e3,
                                           4)}
                    for name, e in sorted(names.items())}
            for scope, names in sorted(self.entries.items())
        }
        if self.memory:
            out["memory"] = {name: dict(cell)
                             for name, cell in sorted(self.memory.items())}
        if self.quality:
            out["quality"] = {
                name: {"buffers": cell.get("buffers", 0),
                       "elems": cell.get("elems", 0),
                       "nan": cell.get("nan", 0),
                       "inf": cell.get("inf", 0)}
                for name, cell in sorted(self.quality.items())}
        return out


#: env var naming the default on-disk ProfileStore directory — the
#: placement planner (runtime/placement.py) and the NNL014 lint hint
#: consult it when no explicit store is handed in; unset = no default
#: store (plan falls back to calibration/heuristics)
STORE_ENV = "NNS_PROFILE_STORE"

#: env var bounding the default store's artifact count (LRU prune on
#: save); unset/0 = unbounded, the pre-PR-10 behavior
STORE_MAX_ENV = "NNS_PROFILE_STORE_MAX"


def default_store() -> Optional["ProfileStore"]:
    """The process-default artifact store (``NNS_PROFILE_STORE`` dir), or
    None when the env var is unset. The directory is created on first
    use (ProfileStore.__init__)."""
    root = os.environ.get(STORE_ENV, "").strip()
    if not root:
        return None
    raw_max = os.environ.get(STORE_MAX_ENV, "").strip()
    try:
        max_artifacts = int(raw_max) if raw_max else None
    except ValueError:
        max_artifacts = None
    return ProfileStore(root, max_artifacts=max_artifacts)


class ProfileStore:
    """On-disk artifact store keyed by (topology, caps, model version).
    ``save(merge=True)`` folds a new capture into the existing artifact
    for the same key, so profiles accumulate across restarts — the
    persistence the placement planner reads at plan time.

    ``max_artifacts`` bounds the store: without it one artifact per
    (topology, caps, model version) accumulates FOREVER across restarts
    — every experiment's one-off launch line leaves a file. When set,
    ``save()`` LRU-prunes (oldest mtime first) down to the bound, and
    the just-saved key always survives (its mtime is newest). ``python
    -m nnstreamer_tpu obs store --prune N`` prunes on demand."""

    def __init__(self, root: str, max_artifacts: Optional[int] = None):
        self.root = root
        self.max_artifacts = max_artifacts
        os.makedirs(root, exist_ok=True)

    @staticmethod
    def _ctx_hash(key: dict) -> str:
        return hashlib.sha256(
            (key.get("caps", "") + "\n" + key.get("model_version", ""))
            .encode()).hexdigest()[:8]

    def path_for(self, key: dict) -> str:
        return os.path.join(
            self.root,
            f"profile-{key.get('topology', 'unknown')}-"
            f"{self._ctx_hash(key)}.json")

    def save(self, artifact: ProfileArtifact, merge: bool = True) -> str:
        path = self.path_for(artifact.key)
        if merge and os.path.exists(path):
            existing = ProfileArtifact.load(path)
            if existing.key == artifact.key:
                artifact = existing.merge(artifact)
        out = artifact.save(path)
        if self.max_artifacts:
            self.prune(self.max_artifacts)
        return out

    def _artifact_paths(self) -> List[str]:
        return [os.path.join(self.root, f)
                for f in sorted(os.listdir(self.root))
                if f.startswith("profile-") and f.endswith(".json")]

    def prune(self, max_artifacts: Optional[int] = None) -> List[str]:
        """LRU-evict artifacts beyond the bound (oldest mtime first —
        ``save()`` rewrites its key's file, so actively-merged keys stay
        newest and cold one-off keys age out). Returns removed paths."""
        bound = max_artifacts if max_artifacts is not None \
            else self.max_artifacts
        if not bound or bound < 1:
            return []
        paths = self._artifact_paths()
        if len(paths) <= bound:
            return []

        def mtime(p: str) -> float:
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0
        victims = sorted(paths, key=lambda p: (mtime(p), p))[:-bound]
        removed = []
        for p in victims:
            try:
                os.remove(p)
                removed.append(p)
            except OSError:
                continue
        return removed

    def load(self, key: dict) -> Optional[ProfileArtifact]:
        path = self.path_for(key)
        if not os.path.exists(path):
            return None
        return ProfileArtifact.load(path)

    def list(self) -> List[dict]:
        out = []
        for fname in sorted(os.listdir(self.root)):
            if fname.startswith("profile-") and fname.endswith(".json"):
                try:
                    art = ProfileArtifact.load(
                        os.path.join(self.root, fname))
                except (OSError, ValueError, KeyError):
                    continue
                out.append({"path": os.path.join(self.root, fname),
                            **art.key})
        return out


# -- text dashboard (obs top) -------------------------------------------------

def render_top(profile_snap: dict, slo_status: List[dict],
               placement: Optional[List[dict]] = None,
               memory: Optional[dict] = None,
               quality: Optional[dict] = None,
               autoscale: Optional[List[dict]] = None,
               fleet: Optional[List[dict]] = None,
               transport: Optional[dict] = None,
               aot: Optional[dict] = None) -> str:
    """The ``obs top`` one-shot/watch dashboard: per-element rates,
    queue waits + depths, fused quantiles, request series, SLO burn,
    a MEMORY section (device watermarks, stage byte estimates, queue
    occupancy — :mod:`.memory`) when a memory snapshot is supplied,
    a QUALITY section (per-edge tensor health + drift — :mod:`.quality`)
    when a quality snapshot is supplied, an AUTOSCALER section (replica
    counts, last decision inputs — service/autoscaler.py) when
    autoscaler snapshots are supplied, and — when a placement plan is
    installed — per-stage device assignment + balance
    (runtime/placement.py)."""
    lines = [f"nns obs top — profiling "
             f"{'ON' if profile_snap.get('active') else 'off'}"]
    if fleet:
        from . import fleet as obs_fleet

        lines.extend(obs_fleet.render_section(fleet))
    for a in autoscale or []:
        last = a.get("last_decision") or {}
        lines.append("")
        lines.append(
            f"AUTOSCALER [{a.get('name', '?')}] replicas "
            f"{a.get('replicas', '?')}/{a.get('desired_replicas', '?')} "
            f"(bounds {a.get('min_replicas', '?')}"
            f"-{a.get('max_replicas', '?')}) "
            f"shed={'ARMED' if a.get('shed_armed') else 'off'}")
        lines.append(
            f"  events: out={a.get('scale_out', 0)} "
            f"in={a.get('scale_in', 0)} "
            f"blocked_by_memory={a.get('blocked_by_memory', 0)} "
            f"respawns={a.get('respawns', 0)} "
            f"gave_up={a.get('respawn_gave_up', 0)}")
        if last:
            lines.append(
                f"  last: {last.get('action', '?'):<16} "
                f"burn {last.get('burn_short', 0):.2f}/"
                f"{last.get('burn_long', 0):.2f} "
                f"(n={last.get('samples_short', 0)}) "
                f"mem {last.get('memory_used_fraction', 0):.2f} "
                f"cooldown out {last.get('out_cooldown_s', 0):.1f}s / "
                f"in {last.get('in_cooldown_s', 0):.1f}s")
    if transport and (transport.get("negotiated") or transport.get("shm")):
        # the data plane (transport/stats.py): which wire formats this
        # process's connections negotiated + shm ring traffic/fallbacks
        lines.append("")
        conns = transport.get("connections", {})
        neg = transport.get("negotiated", {})
        parts = [f"{fmt}:{neg.get(fmt, 0)}"
                 f"({conns.get(fmt, 0)} open)" for fmt in sorted(neg)]
        lines.append("TRANSPORT negotiated " + (" ".join(parts) or "—"))
        frames = transport.get("frames", {})
        nbytes = transport.get("bytes", {})
        if frames:
            lines.append(f"  {'plane':<14} {'frames':>10} {'MB':>10}")
            for key in sorted(frames):
                lines.append(f"  {key:<14} {frames[key]:>10d} "
                             f"{nbytes.get(key, 0) / 1e6:>10.2f}")
        shm = transport.get("shm", {})
        if shm:
            lines.append(
                f"  shm: writes={shm.get('slot_writes', 0)} "
                f"reclaimed={shm.get('reclaimed_slots', 0)} "
                f"full-fallbacks={shm.get('fallback_full', 0)} "
                f"oversize={shm.get('fallback_oversize', 0)} "
                f"segments={shm.get('segments_created', 0)}c/"
                f"{shm.get('segments_attached', 0)}a/"
                f"{shm.get('segments_closed', 0)}x")
    for plan in placement or []:
        lines.append("")
        lines.append(f"PLACEMENT [{plan.get('pipeline', '?')}] "
                     f"source={plan.get('source', '?')} "
                     f"max-stage {plan.get('balance', {}).get('max_stage_ms', 0):.3f}ms "
                     f"/ target {plan.get('balance', {}).get('target_ms', 0):.3f}ms")
        lines.append(f"  {'stage':<40} {'device':>8} {'cost_ms':>9}")
        for st in plan.get("stages", []):
            lines.append(f"  {st['stage']:<40} {st['device']:>8d} "
                         f"{st['cost_ms']:>9.3f}")
        for qname, q in sorted(plan.get("queues", {}).items()):
            lines.append(f"  queue {qname:<34} depth={q['depth']:<4d} "
                         f"(wait p99 {q.get('wait_p99_ms', 0.0):.3f}ms)")
    durations = profile_snap.get("durations", {})
    sections = (("element", "ELEMENTS (per-hop wall time)"),
                ("fused", "FUSED SEGMENTS (host dispatch)"),
                ("fused_device", "FUSED SEGMENTS (device probe)"),
                ("queue_wait", "QUEUE WAIT"),
                ("serving", "SERVING BATCHES"))
    for scope, title in sections:
        names = durations.get(scope)
        if not names:
            continue
        lines.append("")
        lines.append(f"{title}")
        lines.append(f"  {'name':<40} {'rate/s':>8} {'p50ms':>9} "
                     f"{'p99ms':>9} {'maxms':>9} {'n':>8}"
                     + ("  depth" if scope == "queue_wait" else ""))
        for name, s in names.items():
            row = (f"  {name:<40} {s['rate_hz']:>8.1f} {s['p50_ms']:>9.3f} "
                   f"{s['p99_ms']:>9.3f} {s['max_ms']:>9.3f} "
                   f"{s['count']:>8d}")
            if scope == "queue_wait" and "depth" in s:
                row += f"  {s['depth']:>5d}"
            lines.append(row)
    transfers = profile_snap.get("transfers")
    if transfers:
        # NNS_XFERCHECK byte ledger (analysis/sanitizer.py third half):
        # where bytes cross the host/device and process boundaries,
        # largest movers first
        lines.append("")
        lines.append("TRANSFERS (NNS_XFERCHECK byte ledger)")
        lines.append(f"  {'stage':<40} {'dir':>8} {'MiB':>10} {'n':>8}")
        for row in transfers:
            lines.append(
                f"  {row['stage']:<40} {row['direction']:>8} "
                f"{row['bytes'] / (1 << 20):>10.3f} {row['count']:>8d}")
    requests = profile_snap.get("requests", {})
    if requests:
        lines.append("")
        lines.append("REQUESTS")
        lines.append(f"  {'series':<40} {'p50ms':>9} {'p99ms':>9} "
                     f"{'maxms':>9} {'n':>8} {'err':>6}")
        for name, s in requests.items():
            lines.append(
                f"  {name:<40} {s['p50_ms']:>9.2f} {s['p99_ms']:>9.2f} "
                f"{s['max_ms']:>9.2f} {s['count']:>8d} {s['errors']:>6d}")
    if aot and (aot.get("active") or any(aot.get("counters", {}).values())):
        from .. import aot as aot_plane

        # AOT compile-cache section (nnstreamer_tpu/aot): hit/miss/
        # export/eviction totals + the artifact inventory
        lines.extend(aot_plane.render_section(aot))
    if memory:
        from . import memory as obs_memory

        lines.extend(obs_memory.render_section(memory))
    if quality:
        from . import quality as obs_quality

        lines.extend(obs_quality.render_section(quality))
    if slo_status:
        lines.append("")
        lines.append("SLO (burn = bad-fraction / error budget)")
        lines.append(f"  {'objective':<28} {'target':>7} {'window':>10} "
                     f"{'burn':>8} {'state':>9}")
        for st in slo_status:
            state = "BREACH" if st.get("alerting") else "ok"
            for w in st.get("windows", []):
                lines.append(
                    f"  {st['name']:<28} {st['target']:>7.4f} "
                    f"{w['short_s']:>9.0f}s {w['burn_short']:>8.2f} "
                    f"{state:>9}")
                lines.append(
                    f"  {'':<28} {'':>7} {w['long_s']:>9.0f}s "
                    f"{w['burn_long']:>8.2f} {'':>9}")
    return "\n".join(lines)
