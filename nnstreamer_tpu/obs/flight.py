"""Crash flight recorder: always-on bounded ring of recent events (L7).

The supervisor's postmortem question — "what was happening just before
this service stalled/crashed?" — needs history that was being recorded
BEFORE anyone knew to look. This ring records it continuously at
near-zero cost: one ``itertools.count`` tick (exact under the GIL, no
lock) plus one list-slot assignment per event; old events are simply
overwritten. It is never disabled.

What lands here (all low-rate control-plane signals, never per-buffer
dataflow): pipeline lifecycle transitions (playing/stopped/eos/error),
service state changes, supervisor crashes/restarts, fabric
evictions/readmissions/hedges/request errors, serving batch failures,
and — when request tracing is enabled — every finished span.

Consumers: :class:`~nnstreamer_tpu.service.supervisor.CrashReport`
embeds the tail at capture time, ``Service`` DEGRADED transitions log
it, the control plane serves it at ``GET /flight``, and
``python -m nnstreamer_tpu obs flight`` prints it.
"""
from __future__ import annotations

import itertools
import time
from typing import List, Optional


class FlightRecorder:
    """Lock-free bounded event ring.

    Writers race benignly: the sequence counter is exact (itertools under
    the GIL), each slot write is a single atomic list assignment of an
    immutable tuple, and a reader (:meth:`dump`) reconstructs order from
    the per-event sequence numbers — a torn iteration can only miss or
    double-see an event that was being overwritten anyway."""

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1")
        self.capacity = capacity
        self._slots: List[Optional[tuple]] = [None] * capacity
        self._seq = itertools.count()
        self._last = -1  # highest seq handed out (racy read is fine)

    def record(self, kind: str, name: str, data: Optional[dict] = None,
               pipeline: Optional[str] = None) -> None:
        i = next(self._seq)
        self._slots[i % self.capacity] = (
            i, time.time(), kind, name, data, pipeline)
        self._last = i

    def count(self) -> int:
        """Events recorded so far (>= retained)."""
        return self._last + 1

    def dump(self, last: Optional[int] = None,
             pipeline: Optional[str] = None,
             category: Optional[str] = None,
             after: Optional[int] = None) -> List[dict]:
        """The retained events, oldest first; ``last`` keeps only the
        newest N, ``pipeline`` filters on the event's pipeline tag, and
        ``category`` on the event kind (``memory``, ``slo``,
        ``pipeline``, ``serving``, ... — mirrors the pipeline filter, so
        a postmortem can pull one subsystem's channel). ``after`` keeps
        only events with ``seq > after`` — the tail-follow cursor
        (``obs flight --follow``, the fleet scraper's incremental
        pulls): a caller that remembers the last seq it saw gets each
        event exactly once, ring-overwrite permitting."""
        entries = sorted((s for s in list(self._slots) if s is not None),
                         key=lambda s: s[0])
        out = []
        for seq, t, kind, name, data, pipe in entries:
            if after is not None and seq <= after:
                continue
            if pipeline is not None and pipe != pipeline:
                continue
            if category is not None and kind != category:
                continue
            out.append({"seq": seq, "time": t, "kind": kind, "name": name,
                        "data": data, "pipeline": pipe})
        if last is not None:
            out = out[-last:]
        return out

    def clear(self) -> None:
        self._slots = [None] * self.capacity


# the process-wide recorder every subsystem publishes into
recorder = FlightRecorder()
record = recorder.record
dump = recorder.dump
count = recorder.count
