"""Prometheus text-exposition parser: samples, labels, scrape helpers (L7).

Every consumer of a ``GET /metrics`` endpoint in this repo used to carry
its own ad-hoc line splitter (``tools/bench_fabric.py`` grew the first
one); this module is the ONE parser they share — the fleet scraper
(:mod:`.fleet`), the failover/fleet benches, and anything else that
reads the text format an external Prometheus would.

The parser understands exactly what our renderer (:mod:`.metrics`)
emits — and the corners the naive splitters got wrong:

* label VALUES may contain commas, spaces, ``=``, and escaped quotes
  (``\\"``), backslashes (``\\\\``) and newlines (``\\n``) — a
  ``split(",")`` over the label block mis-parses all of them;
* histogram sample suffixes (``_bucket``/``_sum``/``_count``) belong to
  their base metric name, so a prefix match on the base name must not
  swallow them by accident (``nns_fabric_requests_total`` vs
  ``nns_fabric_requests_total_whatever``);
* ``# HELP`` / ``# TYPE`` / blank lines are metadata, not samples.

API surface (stdlib only):

* :func:`parse_samples` — full text → list of (name, labels, value);
* :func:`sample` — one value out of a text blob, matched by name +
  label SUBSET (the caller names the labels it cares about);
* :func:`scrape_metric` / :func:`wait_metric` — the HTTP conveniences
  the benches poll evict/readmit counters with.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

Sample = Tuple[str, Dict[str, str], float]


def _unescape(value: str) -> str:
    out: List[str] = []
    i, n = 0, len(value)
    while i < n:
        c = value[i]
        if c == "\\" and i + 1 < n:
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ("\\", '"'):
                out.append(nxt)
            else:  # unknown escape: keep verbatim (prometheus stance)
                out.append(c)
                out.append(nxt)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_labels(block: str) -> Optional[Dict[str, str]]:
    """``a="x",b="y"`` → dict; None on malformed input (never raises —
    scraped text is remote data)."""
    labels: Dict[str, str] = {}
    i, n = 0, len(block)
    while i < n:
        eq = block.find("=", i)
        if eq < 0:
            return None
        name = block[i:eq].strip().lstrip(",").strip()
        if not name:
            return None
        j = eq + 1
        if j >= n or block[j] != '"':
            return None
        j += 1
        start = j
        while j < n:
            if block[j] == "\\":
                j += 2
                continue
            if block[j] == '"':
                break
            j += 1
        if j >= n:
            return None  # unterminated value
        labels[name] = _unescape(block[start:j])
        i = j + 1
    return labels


def parse_line(line: str) -> Optional[Sample]:
    """One exposition line → (name, labels, value); None for comments,
    blanks, and anything malformed."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    brace = line.find("{")
    if brace >= 0:
        close = line.rfind("}")
        if close < brace:
            return None
        name = line[:brace]
        labels = _parse_labels(line[brace + 1:close])
        if labels is None:
            return None
        rest = line[close + 1:].strip()
    else:
        name, _, rest = line.partition(" ")
        labels = {}
        rest = rest.strip()
    # value may be followed by an optional timestamp — take field one
    value_text = rest.split()[0] if rest else ""
    try:
        value = float(value_text)
    except ValueError:
        return None
    return name, labels, value


def parse_samples(text: str) -> List[Sample]:
    """Every sample in an exposition blob, in order."""
    out: List[Sample] = []
    for line in text.splitlines():
        parsed = parse_line(line)
        if parsed is not None:
            out.append(parsed)
    return out


def sample(text: str, name: str, labels: Optional[Dict[str, str]] = None,
           **label_kw) -> Optional[float]:
    """The first sample named EXACTLY ``name`` whose labels are a
    superset of the requested ones; None when absent. Histogram
    consumers pass the suffixed name (``..._bucket``) explicitly —
    a base-name query never swallows suffixed samples."""
    want = dict(labels or {})
    want.update(label_kw)
    for s_name, s_labels, value in parse_samples(text):
        if s_name != name:
            continue
        if all(s_labels.get(k) == str(v) for k, v in want.items()):
            return value
    return None


def samples_named(text: str, name: str) -> List[Sample]:
    """Every sample of one metric (all label sets)."""
    return [s for s in parse_samples(text) if s[0] == name]


# -- HTTP conveniences (the bench scrape loop) --------------------------------

def fetch(endpoint: str, timeout: float = 5.0) -> str:
    """``GET <endpoint>/metrics`` → exposition text. ``endpoint`` is the
    control-plane base URL (a trailing ``/metrics`` is tolerated)."""
    import urllib.request

    url = endpoint.rstrip("/")
    if not url.endswith("/metrics"):
        url += "/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def scrape_metric(endpoint: str, name: str, timeout: float = 5.0,
                  **labels) -> Optional[float]:
    """One Prometheus sample from a live ``GET /metrics``; None when
    absent (label matching is subset, like :func:`sample`)."""
    return sample(fetch(endpoint, timeout=timeout), name, **labels)


def wait_metric(endpoint: str, name: str, labels: Dict[str, str],
                want: float, timeout: float = 15.0,
                poll_s: float = 0.02) -> Optional[float]:
    """Poll the endpoint until ``name`` reaches ``want``; returns the
    observation time (``time.monotonic()``) or None on timeout — the
    benches' evict/readmit clock reads the same scrape surface a
    monitoring stack would."""
    import http.client

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            v = scrape_metric(endpoint, name, **labels)
        except (OSError, http.client.HTTPException):
            # endpoint mid-restart: connection refused/reset is OSError,
            # but a body that dies mid-read raises IncompleteRead /
            # BadStatusLine (HTTPException, NOT OSError) — keep polling
            v = None
        if v is not None and v >= want:
            return time.monotonic()
        time.sleep(poll_s)
    return None
