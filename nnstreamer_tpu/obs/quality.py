"""Data-plane quality observability: tensor health taps + drift scoring (L7).

Every prior obs layer watches the *control* plane — where time goes
(:mod:`.profile`), where bytes go (:mod:`.memory`), whether requests
succeed (:mod:`.slo`). Nothing ever looks at the tensors themselves: a
model that starts emitting NaNs, saturated logits, or
distribution-drifted outputs sails through the fabric, the SLO engine,
and even a canary promote with zero alerts. The reference frames live
pipeline introspection as a core capability of on-device AI development
(NNStreamer, arxiv 2101.06371); this module is the data-plane twin of
the profiler, built on the same keying and persistence machinery:

* **tensor health taps** — a :class:`~..utils.trace.Tracer` installed by
  :func:`start` rides the existing ``Pad.push`` hook (taps off = the one
  ``trace.ACTIVE`` attribute read every other tracer already pays) and
  samples every ``SAMPLE_EVERY``-th buffer per edge into per-edge
  :class:`TensorHealth` cells: NaN/Inf counts, zero fraction,
  min/max/mean/variance, and a log-bucket value-histogram sketch
  reusing :class:`~.profile.QuantileDigest` (γ = 2: power-of-two
  buckets, so sketches from any tap merge exactly). Cells are keyed by
  the same canonical ``<pipeline>:<stage>`` series names the profiler
  and memory accountant use.

* **device-side fused reduction** — a fused segment's interior hops no
  longer exist, and pulling its whole output to the host would defeat
  fusion; instead ``FusedSegment.dispatch`` feeds sampled outputs to
  :func:`record_fused_outputs`, which runs ONE small jitted reduce per
  tensor (counts + moments + a 64-bucket log₂ histogram) and pulls only
  that tiny result — fused pipelines are observed without defusing.
  Host-side taps on device-resident tensors take the same reduce.

* **baselines + drift scoring** — ``ProfileArtifact.capture`` persists
  the per-edge cells as a ``quality`` section under the same (topology,
  caps, model-version) key (merge = additive counts + exact histogram
  merge). :func:`set_baseline` loads such an artifact as the reference
  distribution; :func:`score_tick` then scores each edge's *fresh*
  samples (the delta since the previous tick, so recovery is
  observable) against its baseline with a PSI-style metric over the
  merged histograms (:func:`psi`). Fresh NaN/Inf at any edge scores
  :data:`NONFINITE_SCORE` outright, baseline or not.

* **the closed loops** — first NaN/Inf per edge and drift-threshold
  crossings land as ``quality`` flight events; ``nns_quality_*`` gauges
  render at ``GET /metrics``; a ``quality``-kind :class:`~.slo.SLObjective`
  samples :func:`worst_score` each tick and can mark a service DEGRADED
  without restart; and :class:`CanaryQuality` gates model promotion —
  ``ModelSlots.promote_canary`` refuses with a typed
  ``QualityGateError`` when the canary's output sketch diverges from
  the primary's (service/models.py).

Cost contract (gated by tools/microbench_overhead.py, same family as
tracing/profiler/memory): with taps off every hook is ONE module-global
check (:data:`ACTIVE` on the fused path, ``trace.ACTIVE`` on the pad
path); sampling cost is one small reduction every ``SAMPLE_EVERY``
buffers per edge. Taps only *read* tensors — byte parity of a sampled
pipeline vs taps-off is exact, asserted in tests/test_quality.py.

Surfaces: ``GET /quality``, ``python -m nnstreamer_tpu obs quality``,
the QUALITY section of ``obs top``. See docs/observability.md
(Quality section) for the tap model and the baseline/drift contract.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis.sanitizer import named_lock
from ..utils.log import logger
from . import flight as obs_flight
from . import metrics as obs_metrics
from .profile import QuantileDigest

# module-global fast path: the fused-dispatch / serving hooks check this
# and only this when the taps are off (the microbench gate measures it);
# the pad tap additionally hides behind trace.ACTIVE (tracer install)
ACTIVE = False

#: sample cadence: one health reduction every N buffers per edge
#: (``start(sample_every=...)`` overrides)
SAMPLE_EVERY = 8

#: drift score assigned when fresh samples contain NaN/Inf the baseline
#: did not — numerically broken beats any distribution argument
NONFINITE_SCORE = 10.0

#: fewer fresh finite samples than this score 0.0 (PSI over a handful of
#: values is noise, not drift)
MIN_SCORE_SAMPLES = 32

# the histogram sketch: QuantileDigest with alpha = 1/3 gives
# γ = (1+α)/(1−α) = 2 exactly — bucket i covers (2^(i−1), 2^i], so the
# host (numpy) and device (jit) reducers compute IDENTICAL bucket
# indices with plain ceil(log2(|v|)), and merge stays exact
HIST_ALPHA = 1.0 / 3.0
HIST_LO, HIST_HI = -32, 32          # clamped index range: 2^-32 .. 2^31
N_BUCKETS = HIST_HI - HIST_LO
MIN_VALUE = QuantileDigest.MIN_VALUE  # |v| at or below → zero bucket


# ---------------------------------------------------------------------------
# reducers: one tensor -> (elems, int counts, float moments, histogram)
# ---------------------------------------------------------------------------
# both paths return the same shape:
#   ivec = [nan, inf, zero, zeroish, n_finite]   (zeroish: 0 < |v| <= MIN
#          collapses into the sketch's zero bucket alongside exact zeros)
#   fvec = [finite_sum, finite_sumsq, finite_min, finite_max]
#   counts = int[N_BUCKETS] of finite |v| > MIN, index ceil(log2|v|)-LO

def _reduce_np(t) -> Optional[Tuple[int, np.ndarray, np.ndarray, np.ndarray]]:
    a = np.asarray(t)
    if a.dtype.kind in "iub":
        a = a.astype(np.float32)
    elif a.dtype.kind != "f":
        return None  # non-numeric payloads (strings) are not tapped
    nan = int(np.isnan(a).sum())
    inf = int(np.isinf(a).sum())
    vals = a[np.isfinite(a)]
    absv = np.abs(vals)
    zero = int((vals == 0).sum())
    zeroish = int((absv <= MIN_VALUE).sum())
    live = absv[absv > MIN_VALUE]
    if live.size:
        idx = np.clip(np.ceil(np.log2(live)), HIST_LO,
                      HIST_HI - 1).astype(np.int64)
        counts = np.bincount(idx - HIST_LO, minlength=N_BUCKETS)
    else:
        counts = np.zeros(N_BUCKETS, np.int64)
    v64 = vals.astype(np.float64, copy=False)
    fvec = np.array([v64.sum(), (v64 * v64).sum(),
                     v64.min() if vals.size else 0.0,
                     v64.max() if vals.size else 0.0], np.float64)
    ivec = np.array([nan, inf, zero, zeroish, vals.size], np.int64)
    return a.size, ivec, fvec, counts


_jitted_reduce = None


def _device_reduce():
    """The jitted device-side reduce (built lazily, cached by jax per
    input signature) — one small fused reduction per sampled tensor, so
    observing a fused pipeline never pulls the full output to the host."""
    global _jitted_reduce
    if _jitted_reduce is None:
        import jax
        import jax.numpy as jnp

        def reduce_fn(x):
            xf = (x if jnp.issubdtype(x.dtype, jnp.floating)
                  else x.astype(jnp.float32))
            nan = jnp.isnan(xf).sum()
            inf = jnp.isinf(xf).sum()
            finite = jnp.isfinite(xf)
            nfin = finite.sum()
            vals = jnp.where(finite, xf, 0.0)
            absv = jnp.abs(vals)
            zero = (finite & (xf == 0)).sum()
            zeroish = (finite & (absv <= MIN_VALUE)).sum()
            live = finite & (absv > MIN_VALUE)
            idx = jnp.clip(
                jnp.ceil(jnp.log2(jnp.where(live, absv, 1.0))),
                HIST_LO, HIST_HI - 1).astype(jnp.int32)
            counts = jnp.zeros((N_BUCKETS,), jnp.int32).at[
                jnp.ravel(idx) - HIST_LO].add(
                jnp.ravel(live).astype(jnp.int32))
            fmin = jnp.where(nfin > 0,
                             jnp.where(finite, xf, jnp.inf).min(), 0.0)
            fmax = jnp.where(nfin > 0,
                             jnp.where(finite, xf, -jnp.inf).max(), 0.0)
            ivec = jnp.stack([nan, inf, zero, zeroish, nfin]).astype(
                jnp.int32)
            fvec = jnp.stack([vals.sum(), (vals * vals).sum(),
                              fmin, fmax]).astype(jnp.float32)
            return ivec, fvec, counts

        _jitted_reduce = jax.jit(reduce_fn)
    return _jitted_reduce


def _reduce_any(t) -> Optional[Tuple[int, np.ndarray, np.ndarray,
                                     np.ndarray]]:
    """Host path for numpy tensors, device path for everything else —
    a host tap on a device-resident array must pull ~70 scalars, never
    the tensor."""
    if isinstance(t, np.ndarray):
        return _reduce_np(t)
    if not hasattr(t, "dtype") or not hasattr(t, "shape"):
        return None
    ivec, fvec, counts = _device_reduce()(t)
    size = 1
    for d in t.shape:
        size *= int(d)
    # nnlint: disable=NNL101 — sampled health probe: pulls three tiny
    # reduction results every SAMPLE_EVERY buffers, by contract
    return (size, np.asarray(ivec).astype(np.int64),
            np.asarray(fvec).astype(np.float64),
            np.asarray(counts).astype(np.int64))


# ---------------------------------------------------------------------------
# the per-edge health cell
# ---------------------------------------------------------------------------

class TensorHealth:
    """Running numerical-health aggregate of one tapped edge: counts,
    moments, extremes, and a power-of-two log-bucket sketch of |value|
    (:class:`QuantileDigest` with γ = 2 — merge is exact, see
    :func:`psi`). All counters are additive, so cells merge across
    replicas/runs by plain addition + digest merge."""

    __slots__ = ("buffers", "elems", "nan", "inf", "zero", "sum", "sumsq",
                 "finite", "min", "max", "hist")

    def __init__(self):
        self.buffers = 0
        self.elems = 0
        self.nan = 0
        self.inf = 0
        self.zero = 0
        self.finite = 0
        self.sum = 0.0
        self.sumsq = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.hist = QuantileDigest(HIST_ALPHA)

    def fold(self, elems: int, ivec, fvec, counts) -> None:
        self.elems += int(elems)
        self.nan += int(ivec[0])
        self.inf += int(ivec[1])
        self.zero += int(ivec[2])
        nfin = int(ivec[4])
        self.finite += nfin
        self.sum += float(fvec[0])
        self.sumsq += float(fvec[1])
        if nfin:
            self.min = min(self.min, float(fvec[2]))
            self.max = max(self.max, float(fvec[3]))
        h = self.hist
        zeroish = int(ivec[3])
        h._zero += zeroish
        h.count += zeroish
        if zeroish:
            h.min = 0.0
        b = h._buckets
        for i in range(N_BUCKETS):
            c = int(counts[i])
            if c:
                k = HIST_LO + i
                b[k] = b.get(k, 0) + c
                h.count += c
                # bucket-derived |v| bounds: enough for quantile()'s
                # clamp at this sketch's factor-2 resolution
                h.min = min(h.min, 2.0 ** (k - 1))
                h.max = max(h.max, 2.0 ** k)

    # -- derived -------------------------------------------------------------
    @property
    def nan_frac(self) -> float:
        return self.nan / self.elems if self.elems else 0.0

    @property
    def inf_frac(self) -> float:
        return self.inf / self.elems if self.elems else 0.0

    @property
    def zero_frac(self) -> float:
        return self.zero / self.elems if self.elems else 0.0

    @property
    def mean(self) -> float:
        return self.sum / self.finite if self.finite else 0.0

    @property
    def variance(self) -> float:
        if not self.finite:
            return 0.0
        m = self.mean
        return max(0.0, self.sumsq / self.finite - m * m)

    def snapshot(self) -> dict:
        return {
            "buffers": self.buffers, "elems": self.elems,
            "nan": self.nan, "inf": self.inf,
            "nan_frac": self.nan_frac, "inf_frac": self.inf_frac,
            "zero_frac": round(self.zero_frac, 6),
            "min": None if not self.finite else self.min,
            "max": None if not self.finite else self.max,
            "mean": self.mean, "variance": self.variance,
        }

    # -- persistence (the artifact `quality` section cell) -------------------
    def to_cell(self, kind: str = "edge") -> dict:
        return {
            "kind": kind, "buffers": self.buffers, "elems": self.elems,
            "nan": self.nan, "inf": self.inf, "zero": self.zero,
            "finite": self.finite, "sum": self.sum, "sumsq": self.sumsq,
            "min": None if not self.finite else self.min,
            "max": None if not self.finite else self.max,
            "hist": self.hist.to_dict(),
        }

    @classmethod
    def from_cell(cls, cell: dict) -> "TensorHealth":
        h = cls()
        h.buffers = int(cell.get("buffers", 0))
        h.elems = int(cell.get("elems", 0))
        h.nan = int(cell.get("nan", 0))
        h.inf = int(cell.get("inf", 0))
        h.zero = int(cell.get("zero", 0))
        h.finite = int(cell.get("finite", 0))
        h.sum = float(cell.get("sum", 0.0))
        h.sumsq = float(cell.get("sumsq", 0.0))
        if cell.get("min") is not None:
            h.min = float(cell["min"])
        if cell.get("max") is not None:
            h.max = float(cell["max"])
        if cell.get("hist"):
            h.hist = QuantileDigest.from_dict(cell["hist"])
        return h


def merge_cells(mine: dict, other: dict) -> dict:
    """Fold another run's serialized quality cell into ``mine`` (in
    place; returns it). Counts add, extremes extend, histograms merge
    exactly — the semantics ``ProfileArtifact.merge`` applies to the
    ``quality`` section (additive, unlike memory's max-watermark: a
    health sketch is a sample population, not a high-water mark)."""
    for f in ("buffers", "elems", "nan", "inf", "zero", "finite"):
        mine[f] = int(mine.get(f, 0)) + int(other.get(f, 0))
    for f in ("sum", "sumsq"):
        mine[f] = float(mine.get(f, 0.0)) + float(other.get(f, 0.0))
    for f, pick in (("min", min), ("max", max)):
        a, b = mine.get(f), other.get(f)
        mine[f] = pick(a, b) if a is not None and b is not None \
            else (a if a is not None else b)
    mine.setdefault("kind", other.get("kind", "edge"))
    a_hist, b_hist = mine.get("hist"), other.get("hist")
    if a_hist and b_hist:
        merged = QuantileDigest.from_dict(a_hist)
        merged.merge(QuantileDigest.from_dict(b_hist))
        mine["hist"] = merged.to_dict()
    elif b_hist:
        mine["hist"] = dict(b_hist)
    return mine


# ---------------------------------------------------------------------------
# PSI drift metric
# ---------------------------------------------------------------------------

def psi(a: QuantileDigest, b: QuantileDigest, epsilon: float = 1e-4
        ) -> float:
    """Population-stability-index between two value sketches: both are
    normalized over the union of their (shared-γ) buckets plus the zero
    bucket, empty cells smoothed to ``epsilon``, and
    ``Σ (p−q)·ln(p/q)`` summed. 0 = identical distributions; the usual
    operating bands apply (< 0.1 stable, 0.1–0.25 drifting, > 0.25
    shifted). Either sketch empty → 0.0 (nothing to compare)."""
    na, nb = a.count, b.count
    if na == 0 or nb == 0:
        return 0.0
    keys = set(a._buckets) | set(b._buckets)
    score = 0.0
    pairs = [(a._zero / na, b._zero / nb)]
    pairs += [(a._buckets.get(k, 0) / na, b._buckets.get(k, 0) / nb)
              for k in keys]
    for p, q in pairs:
        p = max(p, epsilon)
        q = max(q, epsilon)
        score += (p - q) * math.log(p / q)
    return score


# ---------------------------------------------------------------------------
# the accountant
# ---------------------------------------------------------------------------

class QualityAccountant:
    """Process-wide tensor-health store, keyed like the profiler's
    duration series (``<pipeline>:<canonical-stage>`` for pad taps and
    fused segments, ``serving:<scheduler>`` for batch outputs). The
    first NaN/Inf observed on an edge records a ``quality`` flight
    event (once per edge until :meth:`reset`)."""

    def __init__(self):
        self._lock = named_lock("QualityAccountant._lock")
        self._edges: Dict[str, Tuple[str, TensorHealth]] = {}  # guarded-by: _lock
        self._nonfinite_seen: set = set()                      # guarded-by: _lock

    def observe(self, name: str, tensors, kind: str = "edge") -> None:
        """Fold one sampled buffer's tensors into the edge's cell (host
        reduce for numpy tensors, device reduce for device arrays)."""
        reduced = []
        for t in tensors:
            r = _reduce_any(t)
            if r is not None:
                reduced.append(r)
        if not reduced:
            return
        self._fold(name, kind, reduced)

    def observe_reduced(self, name: str, kind: str, reduced) -> None:
        self._fold(name, kind, reduced)

    def _fold(self, name: str, kind: str, reduced) -> None:
        fire = None
        with self._lock:
            entry = self._edges.get(name)
            if entry is None:
                entry = self._edges[name] = (kind, TensorHealth())
            cell = entry[1]
            had_nonfinite = cell.nan + cell.inf > 0
            cell.buffers += 1
            for elems, ivec, fvec, counts in reduced:
                cell.fold(elems, ivec, fvec, counts)
            if (not had_nonfinite and cell.nan + cell.inf > 0
                    and name not in self._nonfinite_seen):
                self._nonfinite_seen.add(name)
                fire = {"stage": name, "nan": cell.nan, "inf": cell.inf}
        if fire is not None:
            pipe = name.split(":", 1)[0] if ":" in name else None
            obs_flight.record("quality", "nonfinite", fire, pipeline=pipe)

    # -- reading -------------------------------------------------------------
    def health(self, name: str) -> Optional[TensorHealth]:
        with self._lock:
            entry = self._edges.get(name)
            return entry[1] if entry is not None else None

    def stages(self, prefix: str = "") -> Dict[str, dict]:
        """Serialized cells (the artifact ``quality`` section shape),
        optionally restricted to one pipeline's prefix — rendered under
        the lock so a concurrent fold cannot race the digest copy."""
        with self._lock:
            return {name: entry[1].to_cell(entry[0])
                    for name, entry in self._edges.items()
                    if name.startswith(prefix)}

    def snapshots(self) -> Dict[str, dict]:
        with self._lock:
            return {name: {"kind": entry[0], **entry[1].snapshot()}
                    for name, entry in sorted(self._edges.items())}

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._nonfinite_seen.clear()


default_accountant = QualityAccountant()


def export_state() -> dict:
    """Raw serialized health cells for cross-process aggregation (the
    ``GET /quality?raw=1`` route the fleet scraper reads): the same
    ``to_cell`` shape the artifact ``quality`` section persists, so the
    fleet merge reuses :func:`merge_cells` — additive counts + exact
    histogram merge, a replica fleet's pooled sample population."""
    return {"cells": default_accountant.stages()}


def accountant() -> QualityAccountant:
    return default_accountant


# -- hot call sites (each caller checks ACTIVE / samples first) ---------------

_reduce_failed: set = set()


def record_fused_outputs(name: str, outputs) -> None:
    """Sampled fused-segment output health (``FusedSegment.dispatch``):
    one jitted reduce per output tensor, device-side. Must never kill
    the dispatch — failures are logged once per segment."""
    try:
        default_accountant.observe(name, outputs, kind="fused")
    except Exception:  # noqa: BLE001 - a tap must never kill dataflow
        if name not in _reduce_failed:
            _reduce_failed.add(name)
            logger.exception("quality tap: fused reduce failed for %s",
                             name)


_serving_n: Dict[str, int] = {}


def observe_outputs(name: str, outputs, kind: str = "serving") -> None:
    """Sampled output tap for the serving schedulers (one call per
    executed batch while the taps are on)."""
    n = _serving_n.get(name, 0)
    _serving_n[name] = n + 1
    if n % SAMPLE_EVERY:
        return
    try:
        default_accountant.observe(name, outputs, kind=kind)
    except Exception:  # noqa: BLE001 - a tap must never kill serving
        if name not in _reduce_failed:
            _reduce_failed.add(name)
            logger.exception("quality tap: serving reduce failed for %s",
                             name)


class _QualityTracer:
    """The pad-hop tap: rides the ``utils.trace`` hook the chrometrace
    and profiler tracers already use, so taps-off cost is exactly the
    one ``trace.ACTIVE`` check ``Pad.push`` always pays. Samples every
    ``SAMPLE_EVERY``-th buffer per edge (per-edge counter cached on the
    element, like the profiler's series-name cache)."""

    NAME = "quality"

    def buffer_flow(self, pad, buf, elapsed_s: float) -> None:
        peer = pad.peer
        if peer is None:
            return
        el = peer.element
        n = el.__dict__.get("_quality_n", 0)
        el.__dict__["_quality_n"] = n + 1
        if n % SAMPLE_EVERY:
            return
        from .profile import series_name

        try:
            default_accountant.observe(series_name(el), buf.tensors)
        except Exception:  # noqa: BLE001 - a tap must never kill dataflow
            name = getattr(el, "name", "?")
            if name not in _reduce_failed:
                _reduce_failed.add(name)
                logger.exception("quality tap: edge reduce failed at %s",
                                 name)

    def results(self) -> dict:
        return default_accountant.snapshots()


# ---------------------------------------------------------------------------
# baselines + drift scoring
# ---------------------------------------------------------------------------

_base_lock = threading.Lock()
_baseline: Dict[str, TensorHealth] = {}       # guarded-by: _base_lock
_drift_threshold = 0.25                       # guarded-by: _base_lock
# per-CONSUMER, per-stage last-seen counters: score_tick() scores the
# DELTA since that consumer's previous tick, so a stage that stops
# emitting bad values cools down and SLO recovery is observable — and
# two concurrent consumers (e.g. two quality SLObjectives on one
# engine) each own a window instead of starving each other
_last_seen: Dict[str, Dict[str, dict]] = {}   # guarded-by: _base_lock
_scores: Dict[str, float] = {}                # guarded-by: _base_lock
_drift_alerting: set = set()  # (consumer, stage)  guarded-by: _base_lock


def set_baseline(source, drift_threshold: float = 0.25) -> None:
    """Install per-edge reference distributions. ``source`` is a
    ``ProfileArtifact`` (its ``quality`` section; stage names are
    pipeline-prefix-stripped, as captured) or a plain
    ``{stage: cell}`` mapping. ``drift_threshold`` is where
    :func:`score_tick` records ``quality`` drift flight events.
    Consumers' fresh-sample windows are PRESERVED: installing a
    baseline mid-life must not re-score history already ticked past
    (NaN from a finished chaos run would read as fresh again)."""
    cells = getattr(source, "quality", None)
    if cells is None:
        cells = source
    loaded = {name: TensorHealth.from_cell(cell)
              for name, cell in dict(cells).items()}
    global _drift_threshold
    with _base_lock:
        _baseline.clear()
        _baseline.update(loaded)
        _drift_threshold = float(drift_threshold)
        _scores.clear()
        _drift_alerting.clear()


def clear_baseline() -> None:
    with _base_lock:
        _baseline.clear()
        _scores.clear()
        _drift_alerting.clear()


def baseline_stages() -> List[str]:
    with _base_lock:
        return sorted(_baseline)


def _strip_pipeline(name: str) -> str:
    return name.split(":", 1)[1] if ":" in name else name


def score_tick(consumer: str = "default") -> Dict[str, float]:
    """Score every tapped edge's FRESH samples (since ``consumer``'s
    previous tick) and return ``{stage: score}``: fresh NaN/Inf →
    :data:`NONFINITE_SCORE`; a baselined stage with enough fresh finite
    samples → PSI of the fresh histogram against the baseline sketch;
    no fresh traffic → 0.0 (cool-down). Crossings of the installed
    drift threshold record ``quality`` flight events both ways. Each
    ``quality``-kind SLO objective calls this through
    :func:`worst_score` with its own consumer key each engine tick —
    windows are per consumer, so concurrent scorers never starve each
    other."""
    live = default_accountant.stages()
    events: List[Tuple[str, str, dict]] = []
    with _base_lock:
        seen = _last_seen.setdefault(consumer, {})
        scores: Dict[str, float] = {}
        for name, cell in live.items():
            prev = seen.get(name)
            seen[name] = cell
            if prev is None:
                # first sighting: score the whole population once
                prev = {"elems": 0, "nan": 0, "inf": 0, "hist": None}
            d_elems = cell["elems"] - prev["elems"]
            if d_elems <= 0:
                scores[name] = 0.0
                continue
            d_nan = cell["nan"] - prev["nan"]
            d_inf = cell["inf"] - prev["inf"]
            if d_nan > 0 or d_inf > 0:
                scores[name] = NONFINITE_SCORE
            else:
                score = 0.0
                base = _baseline.get(_strip_pipeline(name))
                if base is not None:
                    # fresh histogram = cumulative minus the previous
                    # tick's snapshot (counts are monotone, so the
                    # bucket-wise delta is exact and non-negative)
                    fresh = QuantileDigest.from_dict(cell["hist"])
                    if prev["hist"]:
                        old = QuantileDigest.from_dict(prev["hist"])
                        fresh.count -= old.count
                        fresh._zero -= old._zero
                        for k, c in old._buckets.items():
                            fresh._buckets[k] = fresh._buckets.get(k, 0) - c
                    if fresh.count >= MIN_SCORE_SAMPLES:
                        score = psi(base.hist, fresh)
                scores[name] = score
            key = (consumer, name)
            was = key in _drift_alerting
            now = scores[name] >= _drift_threshold
            detail = {"stage": name, "score": round(scores[name], 4)}
            if consumer != "default":
                detail["consumer"] = consumer
            if now and not was:
                _drift_alerting.add(key)
                detail["threshold"] = _drift_threshold
                events.append((name, "drift", detail))
            elif was and not now:
                _drift_alerting.discard(key)
                events.append((name, "drift_clear", detail))
        # the scrape-time view keeps the latest score per stage across
        # all consumers (a gauge row per consumer would churn labels)
        _scores.update(scores)
    for name, kind, detail in events:
        pipe = name.split(":", 1)[0] if ":" in name else None
        obs_flight.record("quality", kind, detail, pipeline=pipe)
    return dict(scores)


def worst_score(consumer: str = "default") -> float:
    """Worst per-edge drift score right now (rotates ``consumer``'s
    tick window) — the sample the ``quality``-kind SLO objective
    records."""
    scores = score_tick(consumer)
    return max(scores.values(), default=0.0)


def drift_scores() -> Dict[str, float]:
    """The scores computed by the most recent :func:`score_tick` — the
    scrape-time view (reading does NOT rotate the tick windows)."""
    with _base_lock:
        return dict(_scores)


# ---------------------------------------------------------------------------
# canary quality gate (service/models.py promote path)
# ---------------------------------------------------------------------------

class QualityGate:
    """The promote gate's thresholds: maximum primary↔canary output
    divergence (:func:`psi` between the two sketches), maximum *new*
    NaN/Inf fraction the canary may introduce over the primary, the
    minimum samples each side needs before a verdict is meaningful, and
    the mirror cadence (every Nth primary invoke is shadow-run through
    the candidate)."""

    def __init__(self, max_divergence: float = 0.25,
                 max_new_nan_frac: float = 0.0,
                 max_new_inf_frac: float = 0.0,
                 min_samples: int = 8, mirror_every: int = 4):
        if max_divergence <= 0:
            raise ValueError(
                f"max_divergence={max_divergence} must be > 0")
        if min_samples < 1:
            raise ValueError(f"min_samples={min_samples} must be >= 1")
        if mirror_every < 1:
            raise ValueError(f"mirror_every={mirror_every} must be >= 1")
        self.max_divergence = float(max_divergence)
        self.max_new_nan_frac = float(max_new_nan_frac)
        self.max_new_inf_frac = float(max_new_inf_frac)
        self.min_samples = int(min_samples)
        self.mirror_every = int(mirror_every)

    @classmethod
    def from_config(cls, cfg) -> Optional["QualityGate"]:
        """None/False → no gate; True/{} → defaults; a dict sets
        fields; a ready instance passes through."""
        if cfg is None or cfg is False:
            return None
        if cfg is True:
            return cls()
        if isinstance(cfg, cls):
            return cfg
        if isinstance(cfg, dict):
            return cls(**cfg)
        raise ValueError(
            f"quality_gate must be a bool, dict, or QualityGate "
            f"(got {type(cfg).__name__})")

    def spec(self) -> dict:
        return {"max_divergence": self.max_divergence,
                "max_new_nan_frac": self.max_new_nan_frac,
                "max_new_inf_frac": self.max_new_inf_frac,
                "min_samples": self.min_samples,
                "mirror_every": self.mirror_every}


class CanaryQuality:
    """Output-divergence monitor for one canary window, shared by every
    bound filter's router. The gate compares ONLY mirrored pairs:
    every ``mirror_every``-th primary-routed invoke records the
    primary's output AND shadow-runs the candidate on the SAME input
    (output discarded, never served) — both sketches are built over an
    identical input population, so :meth:`verdict`'s drift score
    (:func:`psi` plus NaN/Inf deltas) measures the models, never the
    router's input split. A 1% traffic canary still gathers enough
    candidate samples to gate on, and a candidate that *crashes* on
    live inputs fails the gate without a single client-visible error."""

    def __init__(self, gate: QualityGate):
        self.gate = gate
        self._lock = named_lock("CanaryQuality._lock")
        self.primary = TensorHealth()   # guarded-by: _lock
        self.canary = TensorHealth()    # guarded-by: _lock
        self._n = 0                     # guarded-by: _lock
        self.mirrors = 0                # guarded-by: _lock
        self.mirror_failures = 0        # guarded-by: _lock
        self.last_mirror_error = ""     # guarded-by: _lock

    def should_mirror(self) -> bool:
        with self._lock:
            n = self._n
            self._n += 1
            return n % self.gate.mirror_every == 0

    def _fold(self, cell: TensorHealth, outputs) -> None:
        reduced = []
        for t in outputs if isinstance(outputs, (list, tuple)) else [outputs]:
            r = _reduce_any(t)
            if r is not None:
                reduced.append(r)
        with self._lock:
            cell.buffers += 1
            for elems, ivec, fvec, counts in reduced:
                cell.fold(elems, ivec, fvec, counts)

    def observe_primary(self, outputs) -> None:
        try:
            self._fold(self.primary, outputs)
        except Exception:  # noqa: BLE001 - monitor must never fail a request
            logger.exception("canary quality: primary reduce failed")

    def observe_canary(self, outputs, mirrored: bool = False) -> None:
        try:
            self._fold(self.canary, outputs)
            if mirrored:
                with self._lock:
                    self.mirrors += 1
        except Exception:  # noqa: BLE001 - monitor must never fail a request
            logger.exception("canary quality: canary reduce failed")

    def mirror_failed(self, error: BaseException) -> None:
        """The candidate raised on a mirrored live input — recorded as a
        hard gate failure; the client still got the primary's answer."""
        with self._lock:
            self.mirror_failures += 1
            self.last_mirror_error = f"{type(error).__name__}: {error}"[:200]

    def report(self) -> dict:
        with self._lock:
            divergence = psi(self.primary.hist, self.canary.hist)
            return {
                "gate": self.gate.spec(),
                "divergence": round(divergence, 4),
                "new_nan_frac": max(
                    0.0, self.canary.nan_frac - self.primary.nan_frac),
                "new_inf_frac": max(
                    0.0, self.canary.inf_frac - self.primary.inf_frac),
                "primary": self.primary.snapshot(),
                "canary": self.canary.snapshot(),
                "mirrors": self.mirrors,
                "mirror_failures": self.mirror_failures,
                "last_mirror_error": self.last_mirror_error,
            }

    def verdict(self) -> Tuple[bool, str, dict]:
        """(ok, reason, report) — the promote gate's decision. Too few
        samples on either side refuses: an unobserved candidate is not
        a promotable candidate."""
        rep = self.report()
        g = self.gate
        if rep["mirror_failures"] > 0:
            return False, (f"candidate raised on {rep['mirror_failures']} "
                           f"mirrored input(s): "
                           f"{rep['last_mirror_error']}"), rep
        n_p = rep["primary"]["buffers"]
        n_c = rep["canary"]["buffers"]
        if n_p < g.min_samples or n_c < g.min_samples:
            return False, (f"insufficient samples (primary {n_p}, canary "
                           f"{n_c}, need {g.min_samples} each)"), rep
        if rep["new_nan_frac"] > g.max_new_nan_frac:
            return False, (f"canary introduces NaN (frac "
                           f"{rep['new_nan_frac']:.4g} > "
                           f"{g.max_new_nan_frac:g})"), rep
        if rep["new_inf_frac"] > g.max_new_inf_frac:
            return False, (f"canary introduces Inf (frac "
                           f"{rep['new_inf_frac']:.4g} > "
                           f"{g.max_new_inf_frac:g})"), rep
        if rep["divergence"] > g.max_divergence:
            return False, (f"output divergence {rep['divergence']:.4f} > "
                           f"gate {g.max_divergence:g}"), rep
        return True, "", rep


class SpecAcceptanceGate:
    """Promote arbitration for speculative-decode (draft, target) pairs
    (service/models.py slots carry the pair; serving/speculative.py
    produces the rate). Acceptance is a PERFORMANCE contract, not a
    correctness one — speculative output is token-identical to
    target-only by construction — so the gate guards throughput: a
    draft that stops predicting its target decodes SLOWER than no draft
    at all (every round still pays K draft steps + one verify), and a
    candidate pair must not regress the acceptance the fleet currently
    earns.

    ``min_rate``: absolute floor for the candidate pair's acceptance;
    ``max_drop``: largest tolerated drop vs the active pair's recorded
    rate (ignored when no baseline exists yet);
    ``min_rounds``: speculative rounds the candidate must have run
    before a verdict is meaningful (same stance as
    :class:`QualityGate.min_samples`: unobserved ⇒ unpromotable).
    """

    def __init__(self, min_rate: float = 0.0, max_drop: float = 0.15,
                 min_rounds: int = 16):
        if not 0.0 <= min_rate <= 1.0:
            raise ValueError(f"min_rate={min_rate} must be in [0, 1]")
        if max_drop < 0.0:
            raise ValueError(f"max_drop={max_drop} must be >= 0")
        if min_rounds < 1:
            raise ValueError(f"min_rounds={min_rounds} must be >= 1")
        self.min_rate = float(min_rate)
        self.max_drop = float(max_drop)
        self.min_rounds = int(min_rounds)

    @classmethod
    def from_config(cls, cfg) -> Optional["SpecAcceptanceGate"]:
        """Same contract as :meth:`QualityGate.from_config`."""
        if cfg is None or cfg is False:
            return None
        if cfg is True:
            return cls()
        if isinstance(cfg, cls):
            return cfg
        if isinstance(cfg, dict):
            return cls(**cfg)
        raise ValueError(
            f"acceptance_gate must be a bool, dict, or SpecAcceptanceGate "
            f"(got {type(cfg).__name__})")

    def spec(self) -> dict:
        return {"min_rate": self.min_rate, "max_drop": self.max_drop,
                "min_rounds": self.min_rounds}

    def verdict(self, candidate: Optional[dict],
                baseline: Optional[dict] = None) -> Tuple[bool, str]:
        """(ok, reason). ``candidate``/``baseline`` are
        ``{"rate": float, "rounds": int}`` observations (``None`` =
        never observed). A missing or under-sampled candidate refuses;
        a missing baseline gates on the absolute floor only."""
        if candidate is None:
            return False, ("no speculative-acceptance observation for the "
                           "candidate pair (run it under live/canary "
                           "traffic first)")
        rate = float(candidate.get("rate", 0.0))
        rounds = int(candidate.get("rounds", 0))
        if rounds < self.min_rounds:
            return False, (f"insufficient speculative rounds ({rounds} < "
                           f"{self.min_rounds})")
        if rate < self.min_rate:
            return False, (f"acceptance {rate:.3f} below floor "
                           f"{self.min_rate:g}")
        if baseline is not None:
            base = float(baseline.get("rate", 0.0))
            if base - rate > self.max_drop:
                return False, (f"acceptance {rate:.3f} regresses baseline "
                               f"{base:.3f} by more than {self.max_drop:g}")
        return True, ""


GATE_REFUSALS = obs_metrics.counter(
    "nns_quality_gate_refusals_total",
    "canary promotions refused by the output-quality gate")


# ---------------------------------------------------------------------------
# module-level control
# ---------------------------------------------------------------------------

_ctl_lock = threading.Lock()
_tracer: Optional[_QualityTracer] = None


def start(sample_every: int = 8) -> QualityAccountant:
    """Switch the tensor health taps on: installs the pad tracer and
    arms the fused-segment / serving hooks. One health reduction every
    ``sample_every`` buffers per edge."""
    global ACTIVE, SAMPLE_EVERY, _tracer
    from ..utils import trace

    if sample_every < 1:
        raise ValueError(f"sample_every={sample_every} must be >= 1")
    with _ctl_lock:
        SAMPLE_EVERY = int(sample_every)
        if _tracer is None:
            _tracer = _QualityTracer()
            trace.install_tracer(_tracer)
        ACTIVE = True
    return default_accountant


def stop() -> None:
    """Back to the one-global-check fast path (cells are kept;
    :func:`reset` drops them)."""
    global ACTIVE, _tracer
    from ..utils import trace

    with _ctl_lock:
        ACTIVE = False
        if _tracer is not None:
            trace.uninstall_tracer(_tracer)
            _tracer = None


def reset() -> None:
    default_accountant.reset()
    _serving_n.clear()
    _reduce_failed.clear()
    with _base_lock:
        _last_seen.clear()
        _scores.clear()
        _drift_alerting.clear()


# ---------------------------------------------------------------------------
# snapshot + metrics collector + dashboard section
# ---------------------------------------------------------------------------

def snapshot() -> dict:
    """The ``GET /quality`` document: per-edge health, the installed
    baseline's stages, and the latest drift scores."""
    with _base_lock:
        thr = _drift_threshold
    return {
        "active": ACTIVE,
        "sample_every": SAMPLE_EVERY,
        "stages": default_accountant.snapshots(),
        "baseline": baseline_stages(),
        "drift_threshold": thr,
        "drift": drift_scores(),
    }


_G_BUFFERS = obs_metrics.gauge(
    "nns_quality_buffers_sampled_total",
    "buffers sampled by the tensor health taps", ("stage",))
_G_NAN = obs_metrics.gauge(
    "nns_quality_nan_total", "NaN values observed at the tapped edge",
    ("stage",))
_G_INF = obs_metrics.gauge(
    "nns_quality_inf_total", "Inf values observed at the tapped edge",
    ("stage",))
_G_ZERO = obs_metrics.gauge(
    "nns_quality_zero_fraction", "fraction of exactly-zero values",
    ("stage",))
_G_MEAN = obs_metrics.gauge(
    "nns_quality_mean", "running mean of finite values", ("stage",))
_G_DRIFT = obs_metrics.gauge(
    "nns_quality_drift_score",
    "PSI-style drift score of fresh samples (vs baseline; "
    "NONFINITE_SCORE on fresh NaN/Inf)", ("stage",))


def _collect_quality(_registry) -> None:
    for g in (_G_BUFFERS, _G_NAN, _G_INF, _G_ZERO, _G_MEAN, _G_DRIFT):
        g.clear()
    for name, snap in default_accountant.snapshots().items():
        _G_BUFFERS.set(snap["buffers"], stage=name)
        _G_NAN.set(snap["nan"], stage=name)
        _G_INF.set(snap["inf"], stage=name)
        _G_ZERO.set(snap["zero_frac"], stage=name)
        _G_MEAN.set(snap["mean"], stage=name)
    for name, score in drift_scores().items():
        _G_DRIFT.set(score, stage=name)


obs_metrics.register_collector("quality", _collect_quality)


def render_section(q_snap: dict) -> List[str]:
    """The QUALITY section of ``obs top`` (appended by
    ``profile.render_top`` when a quality snapshot is supplied)."""
    lines: List[str] = []
    stages = q_snap.get("stages") or {}
    if not stages:
        return lines
    drift = q_snap.get("drift") or {}
    lines.append("")
    lines.append(f"QUALITY (taps {'ON' if q_snap.get('active') else 'off'}"
                 f", 1/{q_snap.get('sample_every', SAMPLE_EVERY)} sampled)")
    lines.append(f"  {'stage':<40} {'bufs':>6} {'nan':>6} {'inf':>6} "
                 f"{'zero%':>7} {'mean':>11} {'drift':>8}")
    for name, s in sorted(stages.items()):
        d = drift.get(name)
        lines.append(
            f"  {name:<40} {s['buffers']:>6d} {s['nan']:>6d} "
            f"{s['inf']:>6d} {s['zero_frac'] * 100:>6.1f}% "
            f"{s['mean']:>11.4g} "
            + (f"{d:>8.3f}" if d is not None else f"{'—':>8}"))
    return lines
