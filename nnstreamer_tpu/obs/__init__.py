"""nnstreamer_tpu.obs — the unified observability plane (L7).

Reference analog: the GstShark/NNShark tracer ecosystem the reference
delegates profiling to (arxiv 1901.04985, SURVEY §5.1) — but where
GstShark aggregates per-element, this package is REQUEST-scoped and
cross-subsystem. Three pieces, one contract (near-zero cost when idle):

* :mod:`.context` — request-scoped distributed tracing. A
  :class:`~.context.TraceContext` minted where a request enters
  (``QueryClient.request()``, serving admission) propagates through
  fabric retries/hedges (child span per attempt), across the query wire
  (``meta["trace"]``), into the serving batcher (batch spans *link* to
  the N coalesced request spans) and fused device segments
  (``fused:<head>..<tail>`` spans). Export: Perfetto/chrome-trace JSON,
  next to ``utils.trace.jax_trace`` XPlanes. Gated on one module global
  (:data:`~.context.TRACING`).

* :mod:`.metrics` — a Prometheus-style registry serving, service,
  fabric, queue, and fusion sources publish into; rendered at the
  control plane's ``GET /metrics`` route and by
  ``python -m nnstreamer_tpu obs metrics``.

* :mod:`.flight` — the always-on crash flight recorder: a lock-free
  bounded ring of recent control-plane events (state transitions,
  evictions, crashes, spans) dumped into ``CrashReport`` postmortems and
  on DEGRADED transitions, so "why did it stall" is answerable after
  the fact.

* :mod:`.profile` — the continuous profiler: wall time attributed per
  element / fused segment / queue-wait hop into mergeable
  streaming-quantile digests (:class:`~.profile.QuantileDigest`),
  persisted as **profile artifacts** keyed by (topology hash, caps,
  model version) with load/merge/diff APIs — the placement planner's
  and AOT cache's input. Surfaced at ``GET /profile`` and
  ``python -m nnstreamer_tpu obs profile|top``.

* :mod:`.slo` — declarative per-service objectives (p99 latency, error
  rate, availability, memory pressure, output quality) evaluated from
  the same windowed digests with multi-window burn-rate alerting:
  breaches record flight events, export ``nns_slo_*`` gauges, and flip
  the bound Service to DEGRADED through the existing health path.

* :mod:`.quality` — the data plane's numerical health: sampled tensor
  taps on pad hops and fused-segment outputs (NaN/Inf/zero counts,
  moments, a log-bucket value sketch), per-edge baselines persisted in
  the artifact's ``quality`` section, PSI drift scoring against them,
  and the canary promotion quality gate (``QualityGate`` /
  ``CanaryQuality`` — service/models.py refuses promotion with a typed
  ``QualityGateError`` on divergence).

* :mod:`.fleet` — the cross-PROCESS join: a :class:`~.fleet.FleetView`
  scrapes every subprocess replica's control endpoint on a tick thread
  and merges the planes (digests exactly, memory max-watermark, quality
  additively, flight by timestamp), stitches distributed traces across
  the process boundary into one Perfetto document, and serves the SLO
  engine / autoscaler fleet-merged burn windows. ``nns_fleet_*``
  gauges, ``GET /fleet``, ``obs fleet``. :mod:`.promtext` is the shared
  Prometheus text-format parser the scraper and the benches read
  ``GET /metrics`` with.

See docs/observability.md for the span model, propagation rules,
profiling/SLO/quality semantics, the fleet scrape/merge contract, and
the metric name catalog.
"""
from . import (  # noqa: F401
    context,
    fleet,
    flight,
    memory,
    metrics,
    profile,
    promtext,
    quality,
    slo,
)
from .fleet import FleetView  # noqa: F401
from .memory import AdmissionGuard, MemoryAccountant  # noqa: F401
from .quality import (  # noqa: F401
    CanaryQuality,
    QualityAccountant,
    QualityGate,
    TensorHealth,
)
from .context import (  # noqa: F401
    Span,
    TraceContext,
    disable_tracing,
    enable_tracing,
    export_chrome_trace,
    finished_spans,
    record_span,
    spans_for_trace,
    start_span,
)
from .flight import FlightRecorder  # noqa: F401
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricError,
    Registry,
    default_registry,
    render,
)
from .profile import (  # noqa: F401
    ProfileArtifact,
    ProfileStore,
    Profiler,
    QuantileDigest,
    WindowedSeries,
    topology_hash,
)
from .slo import SloEngine, SLObjective  # noqa: F401

__all__ = [
    "AdmissionGuard",
    "CanaryQuality",
    "Counter",
    "FleetView",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MemoryAccountant",
    "MetricError",
    "QualityAccountant",
    "QualityGate",
    "TensorHealth",
    "ProfileArtifact",
    "ProfileStore",
    "Profiler",
    "QuantileDigest",
    "Registry",
    "SLObjective",
    "SloEngine",
    "Span",
    "TraceContext",
    "WindowedSeries",
    "context",
    "default_registry",
    "fleet",
    "disable_tracing",
    "enable_tracing",
    "export_chrome_trace",
    "finished_spans",
    "flight",
    "memory",
    "metrics",
    "profile",
    "promtext",
    "quality",
    "record_span",
    "render",
    "slo",
    "spans_for_trace",
    "start_span",
    "topology_hash",
]
