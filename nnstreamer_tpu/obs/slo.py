"""Declarative SLOs with multi-window burn-rate alerting (L7).

The metrics plane exposes raw counters; ROADMAP item 4's autoscaler and
every on-call page need SLO-grade *judgement*: "is the error budget
burning faster than the objective allows, on both a fast and a slow
window, right now?" This module evaluates exactly that from the
profiler's windowed request digests (:mod:`.profile` —
``WindowedSeries``; digest merge is exact, so a window IS the digest of
its samples).

Objective kinds:

* ``latency`` — good event = request latency <= ``threshold_s``
  (``target`` = required good fraction, e.g. 0.99 ⇒ "p99 under
  threshold"); bad counts come from ``QuantileDigest.count_above``.
* ``error_rate`` — good event = request succeeded.
* ``availability`` — the engine itself samples the bound service's
  readiness each tick into an ``availability:<service>`` series.

**Burn rate** = (bad fraction in window) / (1 - target). Burn 1.0 means
the budget exactly runs out over the objective period; an alert fires
when burn >= the pair's threshold on BOTH the short and the long window
(the standard multi-window construction: the long window proves it is
real, the short window proves it is still happening), and clears when
every short-window burn falls back under its threshold.

On breach: a ``slo`` flight-recorder event, ``nns_slo_*`` gauges on
``GET /metrics``, and — when the objective names a ``service`` — the
Service flips READY → DEGRADED through the existing health path
(``mark_degraded_external``: no supervisor crash, a restart does not fix
overload; routers and fabric health ticks see ``readiness() == False``
and shift load). On recovery the engine flips the services IT degraded
back to READY. ``availability`` objectives never degrade (the service
is already down — alerting only).

Surfaces: ``python -m nnstreamer_tpu obs slo``, the ``slo`` half of
``GET /profile``, ``nns_slo_burn_rate`` / ``nns_slo_alerting`` /
``nns_slo_bad_fraction`` / ``nns_slo_target`` at ``GET /metrics``.
See docs/observability.md (SLO section) for the window math.
"""
from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ..analysis.sanitizer import named_lock
from ..utils.log import logger
from . import flight as obs_flight
from . import metrics as obs_metrics
from . import profile as obs_profile

_KINDS = ("latency", "error_rate", "availability", "memory", "quality")

# default multi-window pairs (short_s, long_s, burn_threshold), sized to
# fit the profiler's default 900 s series horizon; production configs
# with longer horizons pass the classic (5m,1h,14.4)/(30m,6h,6) pairs
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (60.0, 300.0, 14.4),
    (300.0, 900.0, 6.0),
)


@dataclass
class SLObjective:
    """One declarative objective over a request series."""

    name: str
    kind: str = "latency"     # latency | error_rate | availability |
    #                           memory | quality
    series: str = ""                 # e.g. "serving:svc" / "fabric:pool"
    target: float = 0.99             # required good fraction
    threshold_s: float = 0.1         # latency: good = sample <= this;
    #                                  memory: max used-fraction (headroom
    #                                  = 1 - threshold; the engine samples
    #                                  worst-device used/budget each tick);
    #                                  quality: max drift score (the engine
    #                                  samples the worst per-edge PSI drift
    #                                  each tick — obs/quality.worst_score)
    windows: Tuple[Tuple[float, float, float], ...] = DEFAULT_WINDOWS
    service: str = ""                # Service to flip DEGRADED on breach
    description: str = ""

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"kind '{self.kind}' must be one of {_KINDS}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"target={self.target} must be in (0, 1)")
        if self.kind == "availability":
            if not self.service:
                raise ValueError("availability objectives require service=")
            if not self.series:
                self.series = f"availability:{self.service}"
        elif self.kind == "memory":
            if not 0.0 < self.threshold_s <= 1.0:
                raise ValueError(
                    f"memory objectives need threshold_s in (0, 1] "
                    f"(max used fraction), got {self.threshold_s}")
            if not self.series:
                self.series = "memory:devices"
        elif self.kind == "quality":
            if self.threshold_s <= 0.0:
                raise ValueError(
                    f"quality objectives need threshold_s > 0 (max drift "
                    f"score), got {self.threshold_s}")
            if not self.series:
                self.series = "quality:stages"
        elif not self.series:
            raise ValueError(f"objective '{self.name}' needs a series=")
        if not self.windows:
            raise ValueError("at least one (short, long, burn) window pair")
        for w in self.windows:
            if len(w) != 3 or w[0] <= 0 or w[1] < w[0] or w[2] <= 0:
                raise ValueError(
                    f"bad window spec {w}: need (short_s, long_s, "
                    "burn_threshold) with 0 < short <= long, burn > 0")

    def spec(self) -> dict:
        return {"name": self.name, "kind": self.kind, "series": self.series,
                "target": self.target, "threshold_s": self.threshold_s,
                "windows": [list(w) for w in self.windows],
                "service": self.service,
                "description": self.description}


class SloEngine:
    """Evaluates a set of objectives on a tick thread (or on demand via
    :meth:`evaluate` — tests and one-shot CLIs). Starting the engine
    switches the profiler's request recording on
    (:func:`~.profile.enable_recording`)."""

    def __init__(self, manager=None, profiler: Optional[obs_profile.Profiler]
                 = None, tick_s: float = 1.0, name: str = "default"):
        self.name = name
        self.manager = manager
        self.tick_s = tick_s
        self._profiler = (profiler if profiler is not None
                          else obs_profile.default_profiler)
        self._lock = named_lock(f"SloEngine._lock:{name}")
        self._objectives: Dict[str, SLObjective] = {}  # guarded-by: _lock
        self._state: Dict[str, dict] = {}              # guarded-by: _lock
        # services THIS engine flipped DEGRADED, with the set of
        # objectives currently holding them there: two objectives on one
        # service must both recover before the service flips back
        self._degraded: Dict[str, Set[str]] = {}       # guarded-by: _lock
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _engines.add(self)

    # -- configuration -------------------------------------------------------
    def add(self, objective: SLObjective) -> "SloEngine":
        with self._lock:
            self._objectives[objective.name] = objective
        return self

    def remove(self, name: str) -> None:
        with self._lock:
            self._objectives.pop(name, None)
            self._state.pop(name, None)

    def objectives(self) -> List[SLObjective]:
        with self._lock:
            return list(self._objectives.values())

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SloEngine":
        if self._thread is not None:
            return self
        obs_profile.enable_recording()
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"slo:{self.name}",
                                        daemon=True)
        self._thread.start()
        _engines.add(self)  # re-register after a stop()'s discard
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        # the last running engine switches the recording half off (a
        # profile.start() capture session has its own flag and is
        # unaffected either way)
        if not any(e._thread is not None for e in _engines if e is not self):
            obs_profile.disable_recording()
        # leave the status/gauge scrape surface NOW, not when GC collects
        # the weak ref (the PR-10 unregister-at-stop stance; start()
        # re-adds on restart)
        _engines.discard(self)

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.tick_s):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 - the evaluator must outlive
                # a bad tick (a mid-shutdown manager, a racing deregister)
                logger.exception("slo engine %s: evaluation tick failed",
                                 self.name)

    # -- evaluation ----------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass over every objective; returns the new
        status list. Called by the tick thread and directly by tests."""
        t = time.monotonic() if now is None else now
        with self._lock:
            objectives = list(self._objectives.values())
        statuses = []
        for obj in objectives:
            statuses.append(self._evaluate_one(obj, t))
        return statuses

    def _evaluate_one(self, obj: SLObjective, now: float) -> dict:
        if obj.kind == "availability":
            self._sample_availability(obj, now)
        elif obj.kind == "memory":
            self._sample_memory(obj, now)
        elif obj.kind == "quality":
            self._sample_quality(obj, now)
        budget = max(1e-9, 1.0 - obj.target)
        windows = []
        any_pair_breach = False
        all_short_cool = True
        for short_s, long_s, burn_thr in obj.windows:
            b_short, f_short, n_short = self._burn(obj, short_s, budget, now)
            b_long, f_long, n_long = self._burn(obj, long_s, budget, now)
            pair_breach = b_short >= burn_thr and b_long >= burn_thr
            any_pair_breach = any_pair_breach or pair_breach
            all_short_cool = all_short_cool and b_short < burn_thr
            windows.append({
                "short_s": short_s, "long_s": long_s,
                "burn_threshold": burn_thr,
                "burn_short": b_short, "burn_long": b_long,
                "bad_fraction_short": f_short, "bad_fraction_long": f_long,
                "samples_short": n_short, "samples_long": n_long,
                "breaching": pair_breach,
            })
        with self._lock:
            prev = self._state.get(obj.name, {})
            was_alerting = bool(prev.get("alerting"))
            if not was_alerting and any_pair_breach:
                alerting, transition = True, "breach"
            elif was_alerting and all_short_cool:
                # recovery hysteresis: every fast window must cool down
                alerting, transition = False, "recover"
            else:
                alerting, transition = was_alerting, None
            status = {**obj.spec(), "alerting": alerting,
                      "windows": windows,
                      "since": (time.time() if transition
                                else prev.get("since"))}
            self._state[obj.name] = status
        if transition == "breach":
            self._on_breach(obj, windows)
        elif transition == "recover":
            self._on_recover(obj)
        elif alerting:
            self._ensure_degraded(obj, windows)
        return status

    def _burn(self, obj: SLObjective, window_s: float, budget: float,
              now: float) -> Tuple[float, float, int]:
        """(burn rate, bad fraction, sample count) over one window."""
        digest, ok, err = self._profiler.request_window(
            obj.series, window_s, now=now)
        if obj.kind in ("latency", "memory", "quality"):
            # memory samples are used-fractions and quality samples are
            # drift scores: "bad" = a tick whose worst device/edge
            # crossed the threshold — same count_above machinery as
            # latency over seconds
            total = digest.count
            bad = digest.count_above(obj.threshold_s)
        else:
            total = ok + err
            bad = err
        if total == 0:
            return 0.0, 0.0, 0
        frac = bad / total
        return frac / budget, frac, total

    def _sample_availability(self, obj: SLObjective, now: float) -> None:
        svc = self._service(obj.service)
        if svc is None:
            return
        self._profiler.record_request(obj.series, 0.0,
                                      ok=svc.readiness(), now=now)

    def _sample_memory(self, obj: SLObjective, now: float) -> None:
        """Memory-pressure objectives sample themselves each tick, like
        availability: the worst per-device used/budget fraction
        (obs/memory.py — 0.0 when no budget is configured) lands in the
        objective's series; the burn math reads headroom crossings."""
        from . import memory as obs_memory

        self._profiler.record_request(obj.series,
                                      obs_memory.used_fraction(),
                                      ok=True, now=now)

    def _sample_quality(self, obj: SLObjective, now: float) -> None:
        """Quality objectives sample themselves each tick, like memory:
        the worst per-edge drift score (obs/quality.py — fresh NaN/Inf
        score NONFINITE_SCORE, drifted distributions their PSI vs the
        baseline, clean or idle edges 0.0) lands in the objective's
        series; the burn math reads threshold crossings, and recovery
        follows automatically once fresh samples come back clean."""
        from . import quality as obs_quality

        self._profiler.record_request(
            obj.series,
            # per-objective consumer key: each objective owns its own
            # fresh-sample window, so two quality objectives on one
            # engine (or across engines) never starve each other
            obs_quality.worst_score(consumer=f"slo:{self.name}:{obj.name}"),
            ok=True, now=now)

    # -- actions -------------------------------------------------------------
    def _service(self, name: str):
        if self.manager is None or not name:
            return None
        try:
            return self.manager.get(name)
        except Exception:  # noqa: BLE001 - deregistered mid-flight
            return None

    def _on_breach(self, obj: SLObjective, windows: List[dict]) -> None:
        hot = next((w for w in windows if w["breaching"]), windows[0])
        detail = {
            "slo": obj.name, "kind": obj.kind, "series": obj.series,
            "target": obj.target,
            "burn_short": round(hot["burn_short"], 3),
            "burn_long": round(hot["burn_long"], 3),
            "window_s": [hot["short_s"], hot["long_s"]],
            "service": obj.service,
        }
        obs_flight.record("slo", "breach", detail)
        logger.warning(
            "SLO %s BREACH: burn %.1fx/%.1fx over %gs/%gs windows "
            "(target %.4f, series %s)", obj.name, hot["burn_short"],
            hot["burn_long"], hot["short_s"], hot["long_s"], obj.target,
            obj.series)
        self._ensure_degraded(obj, windows)

    def _ensure_degraded(self, obj: SLObjective, windows: List[dict]) -> None:
        # availability breaches never degrade: the service is already
        # down, and degrading it would feed the very signal we sample
        if obj.kind == "availability" or not obj.service:
            return
        with self._lock:
            holders = self._degraded.get(obj.service)
            if holders is not None:
                # the service is already held DOWN by this engine — just
                # register this objective as one more holder, so another
                # objective's recovery cannot flip it back prematurely
                holders.add(obj.name)
                return
        svc = self._service(obj.service)
        if svc is None:
            return
        hot = next((w for w in windows if w["breaching"]), windows[0])
        reason = (f"slo '{obj.name}' burn {hot['burn_short']:.1f}x over "
                  f"{hot['short_s']:g}s (target {obj.target:.4f})")
        if svc.mark_degraded_external(reason):
            with self._lock:
                self._degraded.setdefault(obj.service, set()).add(obj.name)

    def _on_recover(self, obj: SLObjective) -> None:
        obs_flight.record("slo", "recover",
                          {"slo": obj.name, "series": obj.series,
                           "service": obj.service})
        logger.info("SLO %s recovered (series %s)", obj.name, obj.series)
        if not obj.service:
            return
        with self._lock:
            holders = self._degraded.get(obj.service)
            if holders is None:
                return
            holders.discard(obj.name)
            if holders:
                return  # another objective still holds the service down
            del self._degraded[obj.service]
        svc = self._service(obj.service)
        if svc is not None:
            svc.mark_recovered(f"slo '{obj.name}' burn back under "
                               "threshold")

    # -- reading -------------------------------------------------------------
    def status(self) -> List[dict]:
        """The last evaluated status per objective (JSON-friendly; does
        NOT re-evaluate — scrape freshness is the tick cadence)."""
        with self._lock:
            return [dict(self._state.get(o.name, {**o.spec(),
                                                  "alerting": False,
                                                  "windows": []}))
                    for o in self._objectives.values()]


# -- module registry + metrics collector -------------------------------------

_engines: "weakref.WeakSet[SloEngine]" = weakref.WeakSet()


def status_all() -> List[dict]:
    """Status across every live engine (the ``slo`` half of
    ``GET /profile`` and the CLI's ``obs slo`` verb)."""
    out: List[dict] = []
    for engine in list(_engines):
        out.extend(engine.status())
    return out


def _collect_slo(reg: obs_metrics.Registry) -> None:
    burn = reg.gauge("nns_slo_burn_rate",
                     "error-budget burn rate per evaluation window",
                     ("slo", "window"))
    bad = reg.gauge("nns_slo_bad_fraction",
                    "bad-event fraction per evaluation window",
                    ("slo", "window"))
    alerting = reg.gauge("nns_slo_alerting",
                         "1 while the objective's burn alert is firing",
                         ("slo",))
    target = reg.gauge("nns_slo_target", "good-fraction objective",
                       ("slo",))
    # snapshot mirrors: a removed objective's series disappears
    for inst in (burn, bad, alerting, target):
        inst.clear()
    for st in status_all():
        if not st.get("name"):
            continue
        alerting.set(1.0 if st.get("alerting") else 0.0, slo=st["name"])
        target.set(st.get("target", 0.0), slo=st["name"])
        for w in st.get("windows", []):
            burn.set(w["burn_short"], slo=st["name"],
                     window=f"{w['short_s']:g}s")
            burn.set(w["burn_long"], slo=st["name"],
                     window=f"{w['long_s']:g}s")
            bad.set(w["bad_fraction_short"], slo=st["name"],
                    window=f"{w['short_s']:g}s")
            bad.set(w["bad_fraction_long"], slo=st["name"],
                    window=f"{w['long_s']:g}s")


obs_metrics.register_collector("slo", _collect_slo)
