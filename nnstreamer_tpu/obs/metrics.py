"""Unified metrics plane: registry, instruments, Prometheus text (L7).

Before this module every subsystem had its own snapshot silo —
``serving.metrics_snapshot()``, ``service_snapshot()``, ``ReplicaPool
.snapshot()``, fused-segment ``element_stats()`` — and nothing joined
them. Here they all publish into ONE registry, rendered as Prometheus
text exposition at the control plane's ``GET /metrics`` route
(service/api.py) and by ``python -m nnstreamer_tpu obs metrics``.

Two publishing styles:

* **direct instruments** — ``counter()/gauge()/histogram()`` get-or-create
  named instruments; hot-ish paths call ``inc()/set()/observe()``
  (one dict update under a small lock — the fabric's per-request latency
  histogram is the heaviest user, at network-request rate, not
  buffer rate);
* **collectors** — snapshot-shaped sources (a live scheduler, a replica
  pool, a service manager, a fused pipeline) are *tracked weakly* and
  read at scrape time: nothing on their hot paths changes, the scrape
  pays the snapshot cost. ``register_collector()`` adds custom sources.

The built-in collectors cover serving schedulers (``nns_serving_*``),
fabric pools (``nns_fabric_*``), services (``nns_service_*``), fused
device segments (``nns_fused_*``), and the obs plane itself
(``nns_flight_events_total``, ``nns_trace_spans_total``). The full name
catalog lives in docs/observability.md.
"""
from __future__ import annotations

import re
import threading
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..analysis import sanitizer as _san

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class MetricError(ValueError):
    pass


def _escape_label(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class _Instrument:
    KIND = "untyped"

    def __init__(self, name: str, help_text: str,
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise MetricError(f"invalid metric name '{name}'")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise MetricError(f"invalid label name '{ln}' on {name}")
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: Dict[tuple, float] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise MetricError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(_escape_label(labels[ln]) for ln in self.labelnames)

    def _set(self, value: float, labels: dict) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def clear(self) -> None:
        """Drop every sample. Snapshot-mirroring collectors call this
        before repopulating each scrape, so a series whose SOURCE is gone
        (deregistered service, removed replica, a state a service is no
        longer in) disappears instead of reporting its last value
        forever. Never call on directly-incremented instruments."""
        with self._lock:
            self._values.clear()

    def samples(self) -> List[Tuple[str, tuple, float]]:
        """(suffix, label values, value) rows for rendering."""
        with self._lock:
            return [("", k, v) for k, v in sorted(self._values.items())]

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.KIND}"]
        for suffix, key, value in self.samples():
            labels = ""
            if key or suffix:
                pairs = [f'{ln}="{lv}"'
                         for ln, lv in zip(self.labelnames, key[:len(
                             self.labelnames)])]
                pairs += list(key[len(self.labelnames):])  # histogram le=
                labels = "{" + ",".join(pairs) + "}" if pairs else ""
            lines.append(f"{self.name}{suffix}{labels} {_fmt_value(value)}")
        return lines


class Counter(_Instrument):
    """Monotonic counter. ``inc`` accumulates; ``set_total`` mirrors an
    externally-maintained monotonic total (the collector style — the
    source of truth keeps its own counter, we just expose it)."""

    KIND = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set_total(self, value: float, **labels) -> None:
        self._set(value, labels)


class Gauge(_Instrument):
    KIND = "gauge"

    def set(self, value: float, **labels) -> None:
        self._set(value, labels)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Histogram(_Instrument):
    """Cumulative-bucket histogram (Prometheus semantics: ``le`` buckets
    + ``_sum`` + ``_count``)."""

    KIND = "histogram"
    DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                       0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
    # SLO-aligned presets (docs/observability.md#histogram-buckets).
    # STAGE: per-element hops / fused dispatches / queue waits — dense
    # 100 µs–100 ms resolution where stage-latency objectives live, so a
    # bucket edge sits ON every common threshold (1/2.5/5/10/25/50 ms)
    # and burn-rate queries never interpolate across an edge.
    LATENCY_BUCKETS_STAGE = (0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                             0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                             1.0)
    # REQUEST: end-to-end request latency incl. retries/hedges/queueing —
    # edges on the common request SLO thresholds (10/25/50/100/250/500 ms,
    # 1/2.5 s) plus a long tail for timeout forensics.
    LATENCY_BUCKETS_REQUEST = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                               0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

    def __init__(self, name: str, help_text: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_text, labelnames)
        self.buckets = tuple(sorted(buckets))
        # per label-set: [bucket counts..., +Inf count, sum]
        self._hists: Dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            cell = self._hists.get(key)
            if cell is None:
                cell = self._hists[key] = [0] * (len(self.buckets) + 1) + [0.0]
            for i, b in enumerate(self.buckets):
                if value <= b:
                    cell[i] += 1
            cell[len(self.buckets)] += 1  # +Inf / _count
            cell[-1] += float(value)

    def clear(self) -> None:
        with self._lock:
            self._hists.clear()

    def samples(self) -> List[Tuple[str, tuple, float]]:
        rows: List[Tuple[str, tuple, float]] = []
        with self._lock:
            items = sorted(self._hists.items())
        for key, cell in items:
            for i, b in enumerate(self.buckets):
                rows.append(("_bucket", key + (f'le="{b}"',), cell[i]))
            rows.append(("_bucket", key + ('le="+Inf"',),
                         cell[len(self.buckets)]))
            rows.append(("_sum", key, cell[-1]))
            rows.append(("_count", key, cell[len(self.buckets)]))
        return rows


class Registry:
    """Named instruments + scrape-time collectors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Instrument] = {}
        self._collectors: Dict[str, Callable[["Registry"], None]] = {}

    def _get_or_create(self, cls, name: str, help_text: str,
                       labelnames: Sequence[str], **kw):
        with self._lock:
            inst = self._metrics.get(name)
            if inst is None:
                inst = self._metrics[name] = cls(name, help_text,
                                                 labelnames, **kw)
            elif not isinstance(inst, cls) or (
                    inst.labelnames != tuple(labelnames)):
                raise MetricError(
                    f"metric '{name}' already registered as "
                    f"{type(inst).__name__}{inst.labelnames}")
            return inst

    def counter(self, name: str, help_text: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labelnames)

    def gauge(self, name: str, help_text: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labelnames)

    def histogram(self, name: str, help_text: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = Histogram.DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labelnames,
                                   buckets=buckets)

    def register_collector(self, name: str,
                           fn: Callable[["Registry"], None]) -> None:
        """``fn(registry)`` runs at every :meth:`render`; it reads its
        sources and sets instrument values. Re-registering a name
        replaces the collector."""
        with self._lock:
            self._collectors[name] = fn

    def render(self) -> str:
        """Prometheus text exposition (version 0.0.4)."""
        from ..utils.log import logger

        with self._lock:
            collectors = list(self._collectors.items())
        for name, fn in collectors:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 - one bad source must not
                # take the whole scrape down
                logger.exception("obs metrics: collector '%s' failed", name)
        with self._lock:
            instruments = sorted(self._metrics.items())
        lines: List[str] = []
        for _name, inst in instruments:
            lines.extend(inst.render())
        return "\n".join(lines) + "\n"


# -- the default registry + weakly-tracked sources ---------------------------

default_registry = Registry()


def counter(name: str, help_text: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return default_registry.counter(name, help_text, labelnames)


def gauge(name: str, help_text: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return default_registry.gauge(name, help_text, labelnames)


def histogram(name: str, help_text: str = "",
              labelnames: Sequence[str] = (),
              buckets: Sequence[float] = Histogram.DEFAULT_BUCKETS
              ) -> Histogram:
    return default_registry.histogram(name, help_text, labelnames, buckets)


def register_collector(name: str, fn) -> None:
    default_registry.register_collector(name, fn)


def render() -> str:
    return default_registry.render()


# sources register themselves weakly at construction; the collectors
# below read whatever is still alive at scrape time
_tracked_pools: "weakref.WeakSet" = weakref.WeakSet()
_tracked_managers: "weakref.WeakSet" = weakref.WeakSet()
_tracked_pipelines: "weakref.WeakSet" = weakref.WeakSet()


def track_pool(pool) -> None:
    """Called by ``ReplicaPool.__init__`` — pools join the metrics plane
    (and ``serving.metrics_snapshot()``'s fabric fold) automatically."""
    _tracked_pools.add(pool)


def track_manager(manager) -> None:
    _tracked_managers.add(manager)
    if _san.LEAK:
        _san.note_acquire("metrics_registration", f"manager:{id(manager):x}",
                          idempotent=True)


def track_pipeline(pipeline) -> None:
    """Called by ``runtime.fusion.install`` for pipelines with fused
    segments, so one-dispatch chains report dispatch/retrace/defuse
    counters without any pipeline-side publishing code."""
    _tracked_pipelines.add(pipeline)
    if _san.LEAK:
        _san.note_acquire("metrics_registration",
                          f"pipeline:{id(pipeline):x}", idempotent=True,
                          detail=getattr(pipeline, "name", ""))


def untrack_pipeline(pipeline) -> None:
    """Explicit unregister sweep (``Pipeline.stop()`` / service retire):
    the tracked set is weak, but weakness only helps once GC happens to
    run — until then a stopped pipeline's stale ``nns_fused_*`` rows
    keep rendering at every scrape. A replay re-tracks via
    ``fusion.install``."""
    _tracked_pipelines.discard(pipeline)
    if _san.LEAK:
        _san.note_release("metrics_registration",
                          f"pipeline:{id(pipeline):x}")


def untrack_manager(manager) -> None:
    _tracked_managers.discard(manager)
    if _san.LEAK:
        _san.note_release("metrics_registration", f"manager:{id(manager):x}")


def pools_snapshot() -> Dict[str, dict]:
    """{pool_name: ReplicaPool.snapshot()} over every live pool — the
    fabric half of ``serving.metrics_snapshot()`` (per-replica in-flight,
    EWMA health score, evict/readmit/hedge counters in one read)."""
    from ..utils.log import logger

    out: Dict[str, dict] = {}
    for pool in list(_tracked_pools):
        try:
            snap = pool.snapshot()
        except Exception:  # noqa: BLE001 - a closing pool must not break
            # the snapshot the autoscaler polls
            logger.exception("obs metrics: pool snapshot failed")
            continue
        name = snap.get("name", "pool")
        if name in out:  # two pools under one name: keep both visible
            name = f"{name}#{sum(1 for k in out if k.startswith(name))}"
        out[name] = snap
    return out


# -- built-in collectors -----------------------------------------------------

def _collect_serving(reg: Registry) -> None:
    from ..serving import metrics as serving_metrics

    subm = reg.counter("nns_serving_submitted_total",
                       "requests submitted to a scheduler", ("scheduler",))
    comp = reg.counter("nns_serving_completed_total",
                       "requests completed", ("scheduler",))
    fail = reg.counter("nns_serving_failed_total",
                       "requests failed in execution", ("scheduler",))
    shedf = reg.counter("nns_serving_shed_queue_full_total",
                        "requests shed: queue depth", ("scheduler",))
    shedd = reg.counter("nns_serving_shed_deadline_total",
                        "requests shed: deadline budget", ("scheduler",))
    shedm = reg.counter("nns_serving_shed_memory_total",
                        "requests shed: projected memory watermark",
                        ("scheduler",))
    shedo = reg.counter("nns_serving_shed_overload_total",
                        "requests shed: overload guard (autoscaler at "
                        "ceiling)", ("scheduler",))
    batches = reg.counter("nns_serving_batches_total",
                          "device batches executed", ("scheduler",))
    depth = reg.gauge("nns_serving_queue_depth",
                      "requests queued right now", ("scheduler",))
    occ = reg.gauge("nns_serving_batch_occupancy",
                    "real rows / padded rows", ("scheduler",))
    wait = reg.gauge("nns_serving_estimated_wait_seconds",
                     "EWMA-predicted queue wait", ("scheduler",))
    p99 = reg.gauge("nns_serving_latency_p99_seconds",
                    "total request latency p99 (recent window)",
                    ("scheduler",))
    # snapshot mirrors: repopulated from live schedulers each scrape, so
    # a garbage-collected scheduler's series disappears with it
    for inst in (subm, comp, fail, shedf, shedd, shedm, shedo, batches,
                 depth, occ, wait, p99):
        inst.clear()
    for name, sched in serving_metrics.iter_schedulers():
        try:
            snap = sched.metrics_snapshot()
        except Exception:  # noqa: BLE001 - scheduler mid-close
            continue
        subm.set_total(snap.get("submitted", 0), scheduler=name)
        comp.set_total(snap.get("completed", 0), scheduler=name)
        fail.set_total(snap.get("failed", 0), scheduler=name)
        shedf.set_total(snap.get("shed_queue_full", 0), scheduler=name)
        shedd.set_total(snap.get("shed_deadline", 0), scheduler=name)
        shedm.set_total(snap.get("shed_memory", 0), scheduler=name)
        shedo.set_total(snap.get("shed_overload", 0), scheduler=name)
        batches.set_total(snap.get("batches", 0), scheduler=name)
        depth.set(snap.get("queue_depth", 0), scheduler=name)
        occ.set(snap.get("batch_occupancy", 0.0), scheduler=name)
        wait.set(snap.get("estimated_wait_ms", 0.0) / 1e3, scheduler=name)
        p99.set(snap.get("total_latency", {}).get("p99_ms", 0.0) / 1e3,
                scheduler=name)


def _collect_fabric(reg: Registry) -> None:
    pool_counters = {
        "requests": reg.counter("nns_fabric_requests_total",
                                "requests routed through a pool", ("pool",)),
        "retries": reg.counter("nns_fabric_retries_total",
                               "attempts retried on another replica",
                               ("pool",)),
        "hedges": reg.counter("nns_fabric_hedges_total",
                              "hedge duplicates fired", ("pool",)),
        "hedge_wins": reg.counter("nns_fabric_hedge_wins_total",
                                  "hedges that answered first", ("pool",)),
        "request_errors": reg.counter("nns_fabric_request_errors_total",
                                      "requests failed after all attempts",
                                      ("pool",)),
        "evictions": reg.counter("nns_fabric_evictions_total",
                                 "replica evictions", ("pool",)),
        "readmissions": reg.counter("nns_fabric_readmissions_total",
                                    "replica readmissions", ("pool",)),
        "spills": reg.counter("nns_fabric_spills_total",
                              "bounded-load ring spills", ("pool",)),
    }
    inflight = reg.gauge("nns_fabric_inflight",
                         "in-flight requests", ("pool",))
    r_inflight = reg.gauge("nns_fabric_replica_inflight",
                           "per-replica in-flight requests",
                           ("pool", "replica"))
    r_score = reg.gauge("nns_fabric_replica_score",
                        "per-replica EWMA health score",
                        ("pool", "replica"))
    r_up = reg.gauge("nns_fabric_replica_up",
                     "1 = ACTIVE, 0 = quarantined/draining",
                     ("pool", "replica"))
    # snapshot mirrors (NOT the request-latency histogram, which is
    # directly observed): closed pools / removed replicas drop out
    for inst in list(pool_counters.values()) + [inflight, r_inflight,
                                                r_score, r_up]:
        inst.clear()
    for name, snap in pools_snapshot().items():
        for key, inst in pool_counters.items():
            inst.set_total(snap.get(key, 0), pool=name)
        inflight.set(snap.get("inflight_total", 0), pool=name)
        for rep in snap.get("replicas", []):
            rid = rep.get("id", "?")
            r_inflight.set(rep.get("inflight", 0), pool=name, replica=rid)
            r_score.set(rep.get("score", 0.0), pool=name, replica=rid)
            r_up.set(1.0 if rep.get("state") == "active" else 0.0,
                     pool=name, replica=rid)


def _collect_services(reg: Registry) -> None:
    up = reg.gauge("nns_service_up", "1 = READY", ("service",))
    state = reg.gauge("nns_service_state",
                      "1 for the service's current state",
                      ("service", "state"))
    restarts = reg.counter("nns_service_restarts_total",
                           "supervised restarts", ("service",))
    sink = reg.counter("nns_service_sink_buffers_total",
                       "buffers rendered at sinks since last play",
                       ("service",))
    # snapshot mirrors: without the clear, nns_service_state would keep
    # reporting 1 for every state a service was EVER in, and a
    # deregistered service would stay "up" forever
    for inst in (up, state, restarts, sink):
        inst.clear()
    for mgr in list(_tracked_managers):
        try:
            services = mgr.services()
        except Exception:  # noqa: BLE001 - manager mid-shutdown
            continue
        for svc in services:
            up.set(1.0 if svc.readiness() else 0.0, service=svc.name)
            state.set(1.0, service=svc.name, state=svc.state.value)
            restarts.set_total(svc.supervisor.restarts, service=svc.name)
            pipe = svc.pipeline
            if pipe is not None:
                sink.set_total(pipe.sink_buffer_count, service=svc.name)


def _collect_fused(reg: Registry) -> None:
    disp = reg.counter("nns_fused_dispatches_total",
                       "single-XLA-dispatch segment executions",
                       ("pipeline", "segment"))
    retr = reg.counter("nns_fused_retraces_total",
                       "composed-jit retraces", ("pipeline", "segment"))
    defu = reg.counter("nns_fused_defused_total",
                       "runtime fallbacks to per-element dispatch",
                       ("pipeline", "segment"))
    probe = reg.gauge("nns_fused_probe_device_seconds",
                      "last sampled device-complete latency",
                      ("pipeline", "segment"))
    for inst in (disp, retr, defu, probe):  # snapshot mirrors
        inst.clear()
    for pipe in list(_tracked_pipelines):
        for seg in getattr(pipe, "fused_segments", []):
            st = seg.stats
            disp.set_total(st.get("dispatches", 0), pipeline=pipe.name,
                           segment=seg.name)
            retr.set_total(st.get("retraces", 0), pipeline=pipe.name,
                           segment=seg.name)
            defu.set_total(st.get("defused", 0), pipeline=pipe.name,
                           segment=seg.name)
            probe.set(st.get("probe_device_s", 0.0), pipeline=pipe.name,
                      segment=seg.name)


def _collect_wire(reg: Registry) -> None:
    """Data-plane counters (transport/stats.py): negotiated wire formats,
    frames/bytes per format+direction, shm ring events. How a fleet
    silently stuck on the JSON fallback shows up in ``obs fleet``."""
    from ..transport import stats as wire_stats

    conn = reg.gauge("nns_wire_connections",
                     "open query connections by negotiated wire format",
                     ("format",))
    neg = reg.counter("nns_wire_negotiated_total",
                      "handshakes completed by selected wire format",
                      ("format",))
    frames = reg.counter("nns_wire_frames_total",
                         "DATA frames moved", ("format", "direction"))
    nbytes = reg.counter("nns_wire_bytes_total",
                         "DATA payload bytes moved (shm frames count their "
                         "slot bytes, not the descriptor)",
                         ("format", "direction"))
    shm = reg.counter("nns_shm_events_total",
                      "shared-memory ring events (slot_writes, bytes, "
                      "fallback_full, fallback_oversize, reclaimed_slots, "
                      "segments_created/attached/closed)", ("event",))
    for inst in (conn, neg, frames, nbytes, shm):  # snapshot mirrors
        inst.clear()
    snap = wire_stats.snapshot()
    for fmt, v in snap["connections"].items():
        conn.set(v, format=fmt)
    for fmt, v in snap["negotiated"].items():
        neg.set_total(v, format=fmt)
    for key, v in snap["frames"].items():
        fmt, direction = key.rsplit(":", 1)
        frames.set_total(v, format=fmt, direction=direction)
    for key, v in snap["bytes"].items():
        fmt, direction = key.rsplit(":", 1)
        nbytes.set_total(v, format=fmt, direction=direction)
    for event, v in snap["shm"].items():
        shm.set_total(v, event=event)


def _collect_obs(reg: Registry) -> None:
    from . import context, flight

    reg.counter("nns_flight_events_total",
                "events recorded by the flight recorder"
                ).set_total(flight.count())
    st = context.stats()
    reg.counter("nns_trace_spans_total",
                "spans finished since process start"
                ).set_total(st["finished_total"])
    reg.gauge("nns_tracing_enabled",
              "1 when request-scoped tracing is on"
              ).set(1.0 if st["tracing"] else 0.0)


register_collector("serving", _collect_serving)
register_collector("fabric", _collect_fabric)
register_collector("services", _collect_services)
register_collector("fused", _collect_fused)
register_collector("wire", _collect_wire)
register_collector("obs", _collect_obs)
