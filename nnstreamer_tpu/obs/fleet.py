"""Fleet observability: cross-process scrape, merge, and trace stitch (L7).

PR 12 made replicas real OS subprocesses — and silently re-siloed every
observability plane built in PRs 7–11: traces, profile digests, memory
watermarks, quality sketches, and the flight recorder all live inside
ONE process, invisible to the parent that routes, autoscales, and
promotes canaries across them. This module is the parent-side join:

:class:`FleetView`
    Discovers every replica's control endpoint (from a
    :class:`~..service.procreplica.ProcReplicaSet` / ``ReplicaPool``
    via ``control_endpoints()``, or from static endpoints), scrapes
    ``/metrics``, ``/profile?raw=1``, ``/flight?after=``, ``/memory``,
    and ``/quality?raw=1`` on a tick thread with bounded staleness, and
    merges the planes into one coherent fleet snapshot:

    * **latency digests merge EXACTLY** — the PR 8 bucket-wise merge
      guarantee means the fleet p99 IS the pooled p99 (same
      ``QuantileDigest`` algebra, over the wire as bucket dicts);
    * **memory merges max-watermark** per field (a footprint is a
      high-water mark — same semantics as the artifact ``memory``
      section);
    * **quality sketches merge additively** with exact histogram merge
      (a health sketch is a sample population —
      :func:`~.quality.merge_cells`);
    * **flight events interleave by timestamp** with a ``replica`` tag
      into one fleet stream (the ``obs flight --follow --fleet``
      surface), each event stamped with a fleet-local cursor seq.

    Cross-process **trace stitching**: child replicas already mint
    spans for the trace ids that ride the query wire; each process
    exports them wall-clock-annotated at ``GET /spans?trace=``
    (obs/context.py ``export_spans``), and :meth:`FleetView.stitch_trace`
    joins parent + replica spans into ONE Perfetto document — root →
    attempt → the subprocess replica's serving/fused spans, one
    trace_id, per-process ``pid`` lanes named after the replica id.

    **SLO / autoscaler facade**: :meth:`FleetView.request_window` has
    the exact signature the SLO engine and the autoscaler read burn
    rates through (``profiler.request_window``), returning the
    fleet-merged window digest — so ``SloEngine(profiler=fleet)`` and
    ``Autoscaler(..., fleet=fleet)`` compute burn over the MERGED
    series and survive any single replica whose local recorder
    restarted.

Cost contract: the fleet plane adds ZERO hot-path cost — everything
happens on the scrape tick thread (``fleet:<name>``); no data-plane
hook changes. The microbench disabled-path gates are untouched by
construction.

Surfaces: ``nns_fleet_*`` gauges (per-replica labeled + fleet rollups)
at ``GET /metrics``, ``GET /fleet`` on the parent control plane,
``python -m nnstreamer_tpu obs fleet``, and the FLEET section of
``obs top``. See docs/observability.md#fleet for the scrape contract
and per-plane merge semantics.
"""
from __future__ import annotations

import collections
import copy
import itertools
import json
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

from ..analysis.sanitizer import named_lock
from ..utils.log import logger
from . import context as obs_context
from . import flight as obs_flight
from . import metrics as obs_metrics
from . import promtext
from .profile import QuantileDigest

#: duration scopes whose series names carry a ``<pipeline>:`` prefix —
#: replicas of one launch line have DIFFERENT pipeline names (their
#: service name is the ring identity), so the fleet merge strips the
#: prefix to line the same stage up across replicas (the same strip
#: ``ProfileArtifact.capture`` applies)
_PIPELINE_SCOPES = ("element", "fused", "fused_device", "queue_wait")

#: series-name heads that are deployment-shaped, not pipeline-shaped —
#: never stripped
_KEEP_HEADS = ("serving", "fabric")

#: the replica tag the parent process's own planes merge under
PARENT_REPLICA = "_parent"


class FleetError(Exception):
    """Fleet scrape/stitch failure (bad endpoint config, no such view)."""


def fleet_key(name: str) -> str:
    """The fleet-merge key for a series name: the ``<pipeline>:``
    prefix is stripped (replica pipeline names differ by construction)
    unless the head names a deployment-shaped series (``serving:``,
    ``fabric:``)."""
    head, sep, rest = name.partition(":")
    if sep and rest and head not in _KEEP_HEADS:
        return rest
    return name


class _ReplicaScrape:
    """Latest scraped state of one replica's control endpoint. The
    tick thread fetches with no lock held, then PUBLISHES plane +
    health fields under the owning view's lock (one generation at a
    time — a reader can never see tick N's profile beside tick N-1's
    memory); readers snapshot frozen copies via ``_state_rows``.
    ``flight_cursor``/``pid`` are tick-thread-private scrape cursors."""

    __slots__ = ("rid", "endpoint", "ok", "last_ok_t", "last_attempt_t",
                 "scrapes", "errors", "last_error", "profile_raw",
                 "profile_snap", "memory", "quality_cells", "quality_snap",
                 "metrics_text", "flight_cursor", "pid")

    def __init__(self, rid: str, endpoint: str):
        self.rid = rid
        self.endpoint = endpoint
        self.ok = False
        self.last_ok_t = 0.0          # monotonic, 0 = never
        self.last_attempt_t = 0.0
        self.scrapes = 0
        self.errors = 0
        self.last_error: Optional[str] = None
        self.profile_raw: Optional[dict] = None   # export_state() shape
        self.profile_snap: Optional[dict] = None  # snapshot() shape
        self.memory: Optional[dict] = None
        self.quality_cells: Optional[dict] = None
        self.quality_snap: Optional[dict] = None
        self.metrics_text: str = ""
        self.flight_cursor: Optional[int] = None
        self.pid: Optional[int] = None


class FleetView:
    """The parent-side fleet join (see module docstring).

    ``source`` is anything with ``control_endpoints() -> {replica_id:
    url_or_None}`` (``ProcReplicaSet``, ``ReplicaPool``); ``endpoints``
    is a static ``{replica_id: url}`` dict (or a callable returning
    one) for hand-wired fleets and tests. Both compose; membership is
    re-discovered every tick, so scale-out/in and respawns onto new
    ports are followed automatically.

    Threading contract (docs/concurrency.md): ``FleetView._lock`` is a
    LEAF guarding the scraped-state table and the merged flight ring —
    never held across an HTTP call. All scraping happens on the single
    ``fleet:<name>`` tick thread (or a test calling :meth:`tick`
    directly — never both at once). Readers (snapshot/merge/window
    queries) are safe from any thread.
    """

    def __init__(self, name: str, source=None,
                 endpoints=None, *,
                 tick_s: float = 1.0,
                 stale_after_s: float = 5.0,
                 scrape_timeout_s: float = 2.0,
                 flight_capacity: int = 2048,
                 include_parent_flight: bool = True,
                 flight_pull: int = 256,
                 profiler=None):
        if tick_s <= 0:
            raise FleetError(f"tick_s={tick_s} must be > 0")
        if stale_after_s <= 0:
            raise FleetError(f"stale_after_s={stale_after_s} must be > 0")
        if source is None and endpoints is None:
            raise FleetError("FleetView needs a source (ProcReplicaSet/"
                             "ReplicaPool) and/or static endpoints")
        self.name = name
        self.source = source
        self._endpoints = endpoints
        self.tick_s = tick_s
        self.stale_after_s = stale_after_s
        self.scrape_timeout_s = scrape_timeout_s
        self.flight_pull = flight_pull
        self.include_parent_flight = include_parent_flight
        from .profile import default_profiler

        self._local = profiler if profiler is not None else default_profiler
        self._lock = named_lock(f"FleetView._lock:{name}")
        self._states: Dict[str, _ReplicaScrape] = {}   # guarded-by: _lock
        self._flight_ring: "collections.deque[dict]" = collections.deque(
            maxlen=flight_capacity)                    # guarded-by: _lock
        self._fleet_seq = itertools.count()
        self._local_flight_cursor: Optional[int] = None
        self._ticks = 0
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _fleets.add(self)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetView":
        if self._thread is not None:
            return self
        self._stop_evt.clear()
        # re-join the scrape surfaces on restart (stop() discards;
        # same stance as Autoscaler.start())
        _fleets.add(self)
        self._thread = threading.Thread(target=self._loop,
                                        name=f"fleet:{self.name}",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(10.0, self.scrape_timeout_s * 6))
            self._thread = None
        # leave the scrape surfaces NOW, not at GC (same stance as
        # obs_metrics.untrack_*)
        _fleets.discard(self)

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the scraper must outlive
                # one bad tick (a replica dying mid-scrape is the POINT)
                logger.exception("fleet %s: scrape tick failed", self.name)

    # -- discovery -----------------------------------------------------------
    def _discover(self) -> Dict[str, Optional[str]]:
        out: Dict[str, Optional[str]] = {}
        if self.source is not None:
            eps = getattr(self.source, "control_endpoints", None)
            if eps is not None:
                try:
                    out.update(eps())
                except Exception:  # noqa: BLE001 - source mid-teardown
                    logger.exception("fleet %s: endpoint discovery failed",
                                     self.name)
        static = self._endpoints
        if callable(static):
            static = static()
        if static:
            out.update(static)
        return out

    # -- scraping (tick thread only) ------------------------------------------
    def tick(self) -> dict:
        """One scrape pass over the discovered membership; returns a
        compact per-replica outcome dict (tests read it)."""
        members = self._discover()
        now = time.monotonic()
        with self._lock:
            # forget replicas that left the membership (scale-in,
            # breaker discard) — their series leave the merged view
            for rid in [r for r in self._states if r not in members]:
                del self._states[rid]
            for rid, url in members.items():
                st = self._states.get(rid)
                if st is None:
                    st = self._states[rid] = _ReplicaScrape(rid, url or "")
                if url:
                    st.endpoint = url
            states = {rid: self._states[rid] for rid in members}
        outcome: Dict[str, str] = {}
        new_events: List[dict] = []
        for rid, url in members.items():
            st = states[rid]
            if not url:
                with self._lock:
                    st.last_attempt_t = now
                    st.ok = False
                    st.last_error = "no control endpoint (replica dead?)"
                outcome[rid] = "no-endpoint"
                continue
            try:
                planes, events = self._scrape_one(st)
            except Exception as e:  # noqa: BLE001 - a dying replica's
                # half-closed socket raises whatever it raises; the
                # snapshot must stay coherent with its last-known data
                with self._lock:
                    st.last_attempt_t = now
                    st.ok = False
                    st.errors += 1
                    st.last_error = f"{type(e).__name__}: {e}"
                outcome[rid] = "error"
            else:
                new_events.extend(events)
                # publish the whole scrape generation atomically: a
                # reader must never see this tick's profile beside the
                # previous tick's memory, or ok=True with a stale age
                with self._lock:
                    st.last_attempt_t = now
                    for field, value in planes.items():
                        setattr(st, field, value)
                    st.ok = True
                    st.last_ok_t = time.monotonic()
                    st.scrapes += 1
                    st.last_error = None
                outcome[rid] = "ok"
        if self.include_parent_flight:
            # cursored pulls are UNCAPPED: dump keeps the newest N
            # AFTER the cursor filter, so a cap smaller than a burst
            # would drop its oldest events and the advanced cursor
            # would skip them forever; flight_pull only bounds the
            # FIRST (cursorless) backlog pull
            local = obs_flight.dump(
                after=self._local_flight_cursor,
                last=(self.flight_pull if self._local_flight_cursor is None
                      else None))
            if local:
                self._local_flight_cursor = local[-1]["seq"]
                for ev in local:
                    new_events.append({**ev, "replica": PARENT_REPLICA})
        if new_events:
            # interleave by wall timestamp BEFORE assigning fleet seqs,
            # so the merged stream's cursor order is its time order
            new_events.sort(key=lambda ev: ev.get("time", 0.0))
            with self._lock:
                for ev in new_events:
                    ev["fleet_seq"] = next(self._fleet_seq)
                    self._flight_ring.append(ev)
        self._ticks += 1
        return outcome

    def _client(self, endpoint: str):
        from ..service.api import ControlClient

        # retries=0: the tick cadence IS the retry loop, and a wedged
        # endpoint must cost one timeout per tick, not three
        return ControlClient(endpoint, timeout=self.scrape_timeout_s,
                             retries=0)

    def _scrape_one(self, st: _ReplicaScrape
                    ) -> Tuple[Dict[str, object], List[dict]]:
        """All planes of one replica, fetched with NO lock held; raises
        on the CORE scrape (profile) failing, tolerates the satellites.
        Returns (plane-field updates, tagged flight events) for tick()
        to publish under the view's lock; only the tick-thread-private
        flight cursor (``flight_cursor``/``pid``) advances in place. A
        satellite that fails is absent from the updates, so its
        last-known data keeps merging."""
        client = self._client(st.endpoint)
        prof = client.profile(raw=True)
        planes: Dict[str, object] = {
            "profile_raw": prof.get("raw") or {},
            "profile_snap": prof.get("profile") or {},
        }
        try:
            planes["memory"] = client.memory().get("memory")
        except Exception:  # noqa: BLE001 - optional plane
            pass
        try:
            qual = client.quality(raw=True)
            planes["quality_cells"] = qual.get("cells") or {}
            planes["quality_snap"] = qual.get("quality") or {}
        except Exception:  # noqa: BLE001 - optional plane
            pass
        try:
            planes["metrics_text"] = client.metrics_text()
        except Exception:  # noqa: BLE001 - optional plane
            pass
        events: List[dict] = []
        try:
            # cursored pulls fetch uncapped (same stance as the local
            # dump in tick() and obs flight --follow): after= already
            # bounds the reply to new events, and a cap below a burst
            # would lose its oldest events to the advancing cursor
            flight = client.flight(
                last=(self.flight_pull if st.flight_cursor is None
                      else 1_000_000),
                after=st.flight_cursor)
            pid = flight.get("pid")
            if pid is not None:
                if st.pid is not None and pid != st.pid:
                    # the ring identity respawned onto a NEW process:
                    # its recorder (and seq space) restarted at 0, so a
                    # cursor from the old epoch would silently filter
                    # out every post-respawn event — exactly the
                    # postmortem events this stream exists to surface
                    st.flight_cursor = None
                    flight = client.flight(last=self.flight_pull)
                st.pid = pid
            for ev in flight.get("events", []):
                st.flight_cursor = max(st.flight_cursor or -1, ev["seq"])
                events.append({**ev, "replica": st.rid})
        except Exception:  # noqa: BLE001 - optional plane
            pass
        return planes, events

    # -- reading: membership ---------------------------------------------------
    def _state_rows(self) -> List[_ReplicaScrape]:
        # frozen per-replica copies: a reader walks one consistent
        # scrape generation per replica while the tick thread publishes
        # the next one (scraped plane dicts are replaced wholesale,
        # never mutated in place, so shallow copies suffice)
        with self._lock:
            return [copy.copy(st) for st in self._states.values()]

    def replicas(self) -> List[dict]:
        """Per-replica scrape health (age/staleness) — the bounded-
        staleness contract: ``stale`` is True once the last successful
        scrape is older than ``stale_after_s`` (the replica's data is
        still merged — windowed queries age it out by wall time)."""
        now = time.monotonic()
        out = []
        for st in self._state_rows():
            age = (now - st.last_ok_t) if st.last_ok_t else None
            out.append({
                "replica": st.rid,
                "endpoint": st.endpoint,
                "ok": st.ok,
                "stale": age is None or age > self.stale_after_s,
                "age_s": None if age is None else round(age, 3),
                "scrapes": st.scrapes,
                "errors": st.errors,
                "last_error": st.last_error,
                "wire": _wire_summary(st.metrics_text),
            })
        return out

    def metric(self, rid: str, name: str, **labels) -> Optional[float]:
        """One Prometheus sample out of a replica's last ``/metrics``
        scrape (obs/promtext.py); None when absent/never scraped."""
        with self._lock:
            st = self._states.get(rid)
            text = st.metrics_text if st is not None else ""
        return promtext.sample(text, name, **labels) if text else None

    # -- reading: merged planes ------------------------------------------------
    def merged_durations(self) -> Dict[str, Dict[str, dict]]:
        """{scope: {fleet-key: {count, total_s, digest, replicas}}} —
        duration digests merged bucket-wise EXACTLY across replicas
        (fleet p50/p99 == pooled)."""
        out: Dict[str, Dict[str, dict]] = {}
        for st in self._state_rows():
            raw = st.profile_raw or {}
            for scope, names in (raw.get("durations") or {}).items():
                scope_out = out.setdefault(scope, {})
                for name, entry in names.items():
                    key = (fleet_key(name) if scope in _PIPELINE_SCOPES
                           else name)
                    digest = QuantileDigest.from_dict(entry["digest"])
                    cell = scope_out.get(key)
                    if cell is None:
                        scope_out[key] = {
                            "count": int(entry["count"]),
                            "total_s": float(entry["total_s"]),
                            "digest": digest,
                            "replicas": [st.rid],
                        }
                    else:
                        cell["count"] += int(entry["count"])
                        cell["total_s"] += float(entry["total_s"])
                        cell["digest"].merge(digest)
                        cell["replicas"].append(st.rid)
        return out

    def request_series_names(self) -> List[str]:
        names = set()
        for st in self._state_rows():
            names.update((st.profile_raw or {}).get("requests", {}))
        return sorted(names)

    def request_total(self, series: str) -> Optional[QuantileDigest]:
        """The fleet-merged CUMULATIVE digest of one request series —
        bit-for-bit the digest of the pooled samples (the exactness
        property the fleet gauges and tests assert). None when no
        replica exports the series."""
        merged: Optional[QuantileDigest] = None
        for st in self._state_rows():
            req = (st.profile_raw or {}).get("requests", {}).get(series)
            if not req:
                continue
            digest = QuantileDigest.from_dict(req["total"])
            if merged is None:
                merged = digest
            else:
                merged.merge(digest)
        return merged

    def _request_aggregate(self) -> Dict[str, dict]:
        """ONE ``_state_rows()`` walk → every request series' fleet
        rollup: ``{series: {"digest": exact merged QuantileDigest,
        "errors": int, "replicas": [(rid, p99_seconds), ...]}}``.
        ``snapshot()`` and the gauge collector consume this instead of
        re-walking (and re-locking) the scrape state once per series."""
        agg: Dict[str, dict] = {}
        for st in self._state_rows():
            for series, req in (st.profile_raw or {}).get(
                    "requests", {}).items():
                if not req:
                    continue
                digest = QuantileDigest.from_dict(req["total"])
                cell = agg.setdefault(
                    series, {"digest": None, "errors": 0, "replicas": []})
                cell["errors"] += int(req.get("errors", 0))
                cell["replicas"].append((st.rid, digest.quantile(0.99)))
                if cell["digest"] is None:
                    cell["digest"] = digest
                else:
                    cell["digest"].merge(digest)
        return agg

    def request_window(self, series: str, seconds: float,
                       now: Optional[float] = None
                       ) -> Tuple[QuantileDigest, int, int]:
        """(merged digest, ok, err) of one request series over the
        trailing window, across EVERY replica — the profiler-compatible
        read the SLO engine and autoscaler consume
        (``profiler.request_window`` signature). Replica cells are
        wall-clock aligned via each export's monotonic→wall offset, so
        a replica whose process (and monotonic epoch) restarted still
        lands in the right window. Falls back to the LOCAL profiler
        when no replica exports the series (availability/memory/quality
        self-sampled series live parent-side)."""
        t = time.monotonic() if now is None else now
        wall_hi = t + obs_context.mono_to_wall_offset()
        wall_lo = wall_hi - seconds
        merged: Optional[QuantileDigest] = None
        ok = err = 0
        found = False
        for st in self._state_rows():
            raw = st.profile_raw or {}
            req = raw.get("requests", {}).get(series)
            if not req:
                continue
            found = True
            res = float(req.get("resolution_s", 1.0))
            offset = float(raw.get("mono_to_wall", 0.0))
            for cell in req.get("cells", []):
                wall_t = float(cell["epoch"]) * res + offset
                # one-cell tolerance on both edges: cell timestamps are
                # bucket starts and the offset is sampled per scrape
                if wall_lo - res <= wall_t <= wall_hi + res:
                    digest = QuantileDigest.from_dict(cell["digest"])
                    if merged is None:
                        merged = digest
                    else:
                        merged.merge(digest)
                    ok += int(cell.get("ok", 0))
                    err += int(cell.get("err", 0))
        if not found:
            return self._local.request_window(series, seconds, now=now)
        if merged is None:
            merged = QuantileDigest()
        return merged, ok, err

    def record_request(self, series: str, seconds: float, ok: bool = True,
                       now: Optional[float] = None) -> None:
        """Profiler-facade write half: self-sampled SLO series
        (availability / memory / quality kinds) record into the LOCAL
        profiler — ``SloEngine(profiler=fleet)`` needs both halves."""
        self._local.record_request(series, seconds, ok=ok, now=now)

    def merged_memory(self) -> dict:
        """Max-watermark merge of the replicas' memory planes: stage
        estimates per fleet key, device rows per device id — merged
        replicas report the WORST observed footprint, never a sum
        (artifact ``memory`` semantics)."""
        from . import memory as obs_memory

        stages: Dict[str, dict] = {}
        devices: Dict[str, dict] = {}
        for st in self._state_rows():
            mem = st.memory or {}
            for name, cell in (mem.get("stages") or {}).items():
                key = fleet_key(name)
                mine = stages.get(key)
                if mine is None:
                    stages[key] = dict(cell)
                    continue
                for field, value in cell.items():
                    if field == "kind":
                        mine.setdefault("kind", value)
                    elif isinstance(value, (int, float)) and \
                            value > (mine.get(field) or 0):
                        mine[field] = value
                if any(f in mine for f in obs_memory.FIELDS):
                    mine["total_bytes"] = sum(
                        int(mine.get(f, 0) or 0) for f in obs_memory.FIELDS)
            for row in (mem.get("devices") or []):
                dev = row.get("device", "?")
                mine = devices.get(dev)
                if mine is None:
                    devices[dev] = dict(row)
                    continue
                for field, value in row.items():
                    if isinstance(value, (int, float)) and \
                            value > (mine.get(field) or 0):
                        mine[field] = value
        return {"stages": stages,
                "devices": [devices[d] for d in sorted(devices)]}

    def merged_quality(self) -> Dict[str, dict]:
        """Additive merge of the replicas' tensor-health cells per
        fleet key (counts sum, extremes extend, histograms merge
        exactly — :func:`~.quality.merge_cells`)."""
        from . import quality as obs_quality

        out: Dict[str, dict] = {}
        for st in self._state_rows():
            for name, cell in (st.quality_cells or {}).items():
                key = fleet_key(name)
                mine = out.get(key)
                if mine is None:
                    out[key] = dict(cell)
                else:
                    obs_quality.merge_cells(mine, cell)
        return out

    # -- reading: merged flight ------------------------------------------------
    def flight(self, last: Optional[int] = 256,
               category: Optional[str] = None,
               pipeline: Optional[str] = None,
               after: Optional[int] = None) -> List[dict]:
        """The fleet-merged flight stream: replica + parent events
        interleaved by timestamp, each tagged ``replica`` and stamped
        ``fleet_seq`` (the ``--follow`` cursor over the MERGED
        stream)."""
        with self._lock:
            events = list(self._flight_ring)
        out = []
        for ev in events:
            if after is not None and ev["fleet_seq"] <= after:
                continue
            if category is not None and ev.get("kind") != category:
                continue
            if pipeline is not None and ev.get("pipeline") != pipeline:
                continue
            out.append(ev)
        if last is not None:
            out = out[-last:]
        return out

    # -- trace stitching --------------------------------------------------------
    def fetch_spans(self, trace_id: Optional[str] = None,
                    include_local: bool = True) -> List[Tuple[str, dict]]:
        """(label, export) batches: the parent's own spans plus every
        reachable replica's ``GET /spans`` export (a replica that does
        not answer is skipped — stitching is a best-effort postmortem
        read, not a gate)."""
        batches: List[Tuple[str, dict]] = []
        if include_local:
            batches.append((PARENT_REPLICA,
                            obs_context.export_spans(trace_id)))
        for st in self._state_rows():
            if not st.endpoint:
                continue
            try:
                batches.append(
                    (st.rid, self._client(st.endpoint).spans(trace=trace_id)))
            except Exception:  # noqa: BLE001 - unreachable replica
                continue
        return batches

    def stitch_trace(self, trace_id: str,
                     path: Optional[str] = None) -> dict:
        """ONE Perfetto/chrome-trace document for a distributed trace:
        parent spans and every replica's spans for ``trace_id``, placed
        on one wall-clock timeline (each export carries its process's
        monotonic→wall offset), with per-process ``pid`` lanes named
        after the replica id. The cross-process acceptance property:
        root → attempt → the subprocess's serving/fused spans all share
        the SAME ``trace_id`` in the one document."""
        batches = self.fetch_spans(trace_id)
        rows: List[Tuple[str, int, dict]] = []
        for label, batch in batches:
            pid = int(batch.get("pid") or 0)
            for sp in batch.get("spans", []):
                rows.append((label, pid, sp))
        if not rows:
            doc = {"traceEvents": []}
        else:
            t0 = min(sp.get("start_wall_s", 0.0) for _l, _p, sp in rows)
            events = []
            seen_pids: Dict[int, str] = {}
            for label, pid, sp in rows:
                seen_pids.setdefault(pid, label)
                events.append({
                    "name": sp["name"],
                    "cat": sp["kind"],
                    "ph": "X",
                    "ts": (sp.get("start_wall_s", t0) - t0) * 1e6,
                    "dur": sp.get("dur_s", 0.0) * 1e6,
                    "pid": pid,
                    "tid": sp.get("tid", 0),
                    # span attrs spread FIRST: the stitch's own keys
                    # (replica lane, ids) must win a collision — a
                    # fabric attempt span carries attrs={"replica": ...}
                    # that would otherwise shadow the exporting lane
                    "args": {
                        **(sp.get("attrs") or {}),
                        "trace_id": sp["trace_id"],
                        "span_id": sp["span_id"],
                        "parent_span_id": sp.get("parent_span_id"),
                        "status": sp.get("status", "ok"),
                        "links": sp.get("links", []),
                        "replica": label,
                    },
                })
            for pid, label in seen_pids.items():
                events.append({"name": "process_name", "ph": "M",
                               "pid": pid, "tid": 0,
                               "args": {"name": f"{self.name}:{label}"}})
            doc = {"traceEvents": events}
        if path:
            with open(path, "w") as fh:
                json.dump(doc, fh)
        return doc

    # -- snapshot ----------------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``GET /fleet`` document: membership health + every
        merged plane rendered JSON-friendly."""
        durations = {
            scope: {
                name: {
                    "count": cell["count"],
                    "total_s": round(cell["total_s"], 6),
                    "p50_ms": cell["digest"].quantile(0.5) * 1e3,
                    "p99_ms": cell["digest"].quantile(0.99) * 1e3,
                    "replicas": len(cell["replicas"]),
                }
                for name, cell in sorted(names.items())
            }
            for scope, names in self.merged_durations().items()
        }
        requests = {}
        for series, cell in sorted(self._request_aggregate().items()):
            digest = cell["digest"]
            requests[series] = {
                "count": digest.count,
                "errors": cell["errors"],
                "p50_ms": digest.quantile(0.5) * 1e3,
                "p99_ms": digest.quantile(0.99) * 1e3,
            }
        quality = {}
        from .quality import TensorHealth

        for key, cell in sorted(self.merged_quality().items()):
            health = TensorHealth.from_cell(cell)
            quality[key] = {"kind": cell.get("kind", "edge"),
                            **health.snapshot()}
        with self._lock:
            buffered = len(self._flight_ring)
        return {
            "name": self.name,
            "tick_s": self.tick_s,
            "stale_after_s": self.stale_after_s,
            "ticks": self._ticks,
            "replicas": self.replicas(),
            "profile": {"durations": durations, "requests": requests},
            "memory": self.merged_memory(),
            "quality": quality,
            "flight_buffered": buffered,
        }


# ---------------------------------------------------------------------------
# module registry + GET /fleet + metrics collector + obs top section
# ---------------------------------------------------------------------------

_fleets: "weakref.WeakSet[FleetView]" = weakref.WeakSet()


def views() -> List[FleetView]:
    return list(_fleets)


def view(name: Optional[str] = None) -> Optional[FleetView]:
    """The named live view (or, when ``name`` is None, the live view
    with the lexicographically-smallest name — WeakSet iteration order
    is arbitrary, and a follow client's ``fleet_seq`` cursor must hit
    the SAME view on every poll or it filters against the wrong seq
    space)."""
    live = views()
    if name is None:
        return min(live, key=lambda v: v.name) if live else None
    for v in live:
        if v.name == name:
            return v
    return None


def snapshot_all() -> List[dict]:
    """Snapshot across every live fleet view (``GET /fleet``, the CLI's
    ``obs fleet`` verb, ``obs top``'s FLEET section)."""
    return [v.snapshot() for v in views()]


def _collect_fleet(reg: obs_metrics.Registry) -> None:
    replicas_g = reg.gauge("nns_fleet_replicas",
                           "replicas in the fleet view's membership",
                           ("fleet",))
    stale_g = reg.gauge("nns_fleet_replicas_stale",
                        "replicas whose last good scrape is older than "
                        "the staleness bound", ("fleet",))
    up = reg.gauge("nns_fleet_replica_up",
                   "1 = last scrape succeeded and is fresh",
                   ("fleet", "replica"))
    age = reg.gauge("nns_fleet_scrape_age_seconds",
                    "age of the replica's last good scrape",
                    ("fleet", "replica"))
    scrapes = reg.counter("nns_fleet_scrapes_total",
                          "successful control-plane scrapes",
                          ("fleet", "replica"))
    errors = reg.counter("nns_fleet_scrape_errors_total",
                         "failed control-plane scrapes",
                         ("fleet", "replica"))
    req_p99 = reg.gauge("nns_fleet_request_p99_seconds",
                        "fleet-merged request p99 (exact pooled digest)",
                        ("fleet", "series"))
    # GAUGES, not counters: the merged value is a sum over the
    # replicas' live exports, and a replica restart (recorder wiped) or
    # scale-in makes it DECREASE while nonzero — which rate() would
    # misread as a counter reset and report as a huge spurious spike
    req_count = reg.gauge("nns_fleet_request_count",
                          "fleet-merged request count per series "
                          "(sum over live replica exports)",
                          ("fleet", "series"))
    req_err = reg.gauge("nns_fleet_request_errors",
                        "fleet-merged request errors per series "
                        "(sum over live replica exports)",
                        ("fleet", "series"))
    r_p99 = reg.gauge("nns_fleet_replica_request_p99_seconds",
                      "per-replica request p99 per series",
                      ("fleet", "replica", "series"))
    for inst in (replicas_g, stale_g, up, age, scrapes, errors, req_p99,
                 req_count, req_err, r_p99):
        inst.clear()
    for v in views():
        rows = v.replicas()
        replicas_g.set(len(rows), fleet=v.name)
        stale_g.set(sum(1 for r in rows if r["stale"]), fleet=v.name)
        for r in rows:
            up.set(0.0 if r["stale"] or not r["ok"] else 1.0,
                   fleet=v.name, replica=r["replica"])
            if r["age_s"] is not None:
                age.set(r["age_s"], fleet=v.name, replica=r["replica"])
            scrapes.set_total(r["scrapes"], fleet=v.name,
                              replica=r["replica"])
            errors.set_total(r["errors"], fleet=v.name,
                             replica=r["replica"])
        for series, cell in v._request_aggregate().items():
            total = cell["digest"]
            req_p99.set(total.quantile(0.99), fleet=v.name, series=series)
            req_count.set(total.count, fleet=v.name, series=series)
            for rid, p99 in cell["replicas"]:
                r_p99.set(p99, fleet=v.name, replica=rid, series=series)
            req_err.set(cell["errors"], fleet=v.name, series=series)


obs_metrics.register_collector("fleet", _collect_fleet)


def _wire_summary(metrics_text: str) -> Optional[str]:
    """Condense a replica's ``nns_wire_*`` samples (last ``/metrics``
    scrape) into one label: ``"binary+shm"``, ``"binary"``, ``"json"``,
    a comma list when connections are mixed, None before any handshake.
    This is how a replica silently stuck on the JSON fallback shows in
    ``obs fleet`` / the FLEET section of ``obs top``."""
    if not metrics_text:
        return None
    formats = sorted(
        {labels.get("format", "?")
         for name, labels, value in promtext.parse_samples(metrics_text)
         if name == "nns_wire_connections" and value > 0})
    if not formats:
        return None
    shm = promtext.sample(metrics_text, "nns_shm_events_total",
                          event="slot_writes")
    tag = ",".join(formats)
    return tag + "+shm" if shm else tag


def render_section(fleet_snaps: List[dict]) -> List[str]:
    """The FLEET section of ``obs top`` (appended by
    ``profile.render_top`` when fleet snapshots are supplied)."""
    lines: List[str] = []
    for snap in fleet_snaps or []:
        lines.append("")
        rows = snap.get("replicas", [])
        stale = sum(1 for r in rows if r.get("stale"))
        lines.append(f"FLEET [{snap.get('name', '?')}] "
                     f"{len(rows)} replica(s), {stale} stale "
                     f"(tick {snap.get('tick_s', 0):g}s, "
                     f"stale after {snap.get('stale_after_s', 0):g}s)")
        lines.append(f"  {'replica':<28} {'state':>7} {'age_s':>7} "
                     f"{'scrapes':>8} {'errors':>7} {'wire':>11}")
        for r in rows:
            state = ("STALE" if r.get("stale")
                     else "ok" if r.get("ok") else "error")
            age_s = r.get("age_s")
            lines.append(
                f"  {r['replica']:<28} {state:>7} "
                f"{'—' if age_s is None else f'{age_s:.1f}':>7} "
                f"{r.get('scrapes', 0):>8d} {r.get('errors', 0):>7d} "
                f"{r.get('wire') or '—':>11}")
        requests = snap.get("profile", {}).get("requests", {})
        if requests:
            lines.append(f"  {'merged series':<28} {'p50ms':>9} "
                         f"{'p99ms':>9} {'n':>8} {'err':>6}")
            for name, s in sorted(requests.items()):
                lines.append(
                    f"  {name:<28} {s['p50_ms']:>9.2f} {s['p99_ms']:>9.2f} "
                    f"{s['count']:>8d} {s['errors']:>6d}")
    return lines
