"""Native (C++) host-runtime bindings.

The reference implements its allocator, queues, and dataset reader in C
(gst/nnstreamer/tensor_allocator.c, GStreamer queue, gst/datarepo/). Our
equivalents live in ``csrc/nns_core.cc`` — built on demand with g++ into
``libnns_core.so`` and consumed through ctypes. Every consumer has a pure
Python fallback: ``available()`` gates the fast path.

Exposed wrappers:
  * :class:`BufferPool` — aligned, reusing host block pool (staging buffers).
  * :class:`Ring` — bounded SPSC ring of (pointer, size, tag) records.
  * :class:`RepoReader` — background pread prefetcher over a sample file.
  * :func:`gather` / :func:`scatter` — multi-part memcpy without Python joins.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional, Sequence

import numpy as np

from ._build import load_once

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libnns_core.so")
_SRC = os.path.join(_HERE, "csrc", "nns_core.cc")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False

ABI_VERSION = 1


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        lib = load_once(_SRC, _LIB_PATH, ABI_VERSION, "nns_abi_version",
                        _bind, extra_args=("-lpthread",))
        if lib is None:
            _build_failed = True
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    u64, i64, vp = ctypes.c_uint64, ctypes.c_int64, ctypes.c_void_p
    lib.nns_pool_create.restype = vp
    lib.nns_pool_create.argtypes = [u64, u64, u64]
    lib.nns_pool_acquire.restype = vp
    lib.nns_pool_acquire.argtypes = [vp]
    lib.nns_pool_release.argtypes = [vp, vp]
    lib.nns_pool_stats.restype = u64
    lib.nns_pool_stats.argtypes = [vp, ctypes.POINTER(u64)]
    lib.nns_pool_destroy.argtypes = [vp]

    lib.nns_ring_create.restype = vp
    lib.nns_ring_create.argtypes = [u64]
    lib.nns_ring_push.restype = ctypes.c_int
    lib.nns_ring_push.argtypes = [vp, vp, u64, u64, i64]
    lib.nns_ring_pop.restype = ctypes.c_int
    lib.nns_ring_pop.argtypes = [
        vp, ctypes.POINTER(vp), ctypes.POINTER(u64), ctypes.POINTER(u64), i64,
    ]
    lib.nns_ring_close.argtypes = [vp]
    lib.nns_ring_destroy.argtypes = [vp]

    lib.nns_memcpy_gather.argtypes = [
        vp, ctypes.POINTER(vp), ctypes.POINTER(u64), u64,
    ]
    lib.nns_memcpy_scatter.argtypes = [
        vp, ctypes.POINTER(vp), ctypes.POINTER(u64), u64,
    ]

    lib.nns_repo_open.restype = vp
    lib.nns_repo_open.argtypes = [
        ctypes.c_char_p, u64, ctypes.POINTER(u64), u64, vp, u64,
    ]
    lib.nns_repo_next.restype = ctypes.c_int
    lib.nns_repo_next.argtypes = [vp, ctypes.POINTER(vp), ctypes.POINTER(u64), i64]
    lib.nns_repo_release.argtypes = [vp, vp]
    lib.nns_repo_error.restype = ctypes.c_int
    lib.nns_repo_error.argtypes = [vp]
    lib.nns_repo_cancel.argtypes = [vp]
    lib.nns_repo_close.argtypes = [vp]
    lib.nns_abi_version.restype = u64


def available() -> bool:
    """True when the native library is (buildable and) loaded."""
    if os.environ.get("NNS_DISABLE_NATIVE"):
        return False
    return _load() is not None


def _as_numpy(ptr: int, nbytes: int) -> np.ndarray:
    """Zero-copy uint8 view over a native block (caller controls lifetime)."""
    buf = (ctypes.c_uint8 * nbytes).from_address(ptr)
    return np.frombuffer(buf, dtype=np.uint8)


class BufferPool:
    """Aligned reusing block pool (tensor_allocator.c analog)."""

    def __init__(self, block_size: int, alignment: int = 64, max_blocks: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self.block_size = block_size
        self._h = lib.nns_pool_create(block_size, alignment, max_blocks)

    def acquire(self) -> Optional[int]:
        p = self._lib.nns_pool_acquire(self._h)
        return p or None

    def acquire_array(self):
        """Returns ``(uint8 view, block_ptr)`` or None; pass ``block_ptr``
        back to :meth:`release` when done."""
        p = self.acquire()
        if p is None:
            return None
        return _as_numpy(p, self.block_size), p

    def release(self, block: int) -> None:
        self._lib.nns_pool_release(self._h, block)

    def stats(self) -> dict:
        reuses = ctypes.c_uint64()
        acquires = self._lib.nns_pool_stats(self._h, ctypes.byref(reuses))
        return {"acquires": int(acquires), "reuses": int(reuses.value)}

    def close(self) -> None:
        if self._h:
            self._lib.nns_pool_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self.close()
        except Exception:
            pass


class Ring:
    """Bounded SPSC ring of (pointer, size, tag) records."""

    def __init__(self, capacity: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.nns_ring_create(capacity)

    def push(self, ptr: int, size: int, tag: int = 0,
             timeout_ms: int = -1) -> bool:
        return bool(self._lib.nns_ring_push(self._h, ptr, size, tag, timeout_ms))

    def pop(self, timeout_ms: int = -1):
        """Returns (ptr, size, tag) or None on timeout; raises EOFError when
        the ring is closed and drained."""
        data = ctypes.c_void_p()
        size = ctypes.c_uint64()
        tag = ctypes.c_uint64()
        r = self._lib.nns_ring_pop(
            self._h, ctypes.byref(data), ctypes.byref(size),
            ctypes.byref(tag), timeout_ms,
        )
        if r == 1:
            return data.value, size.value, tag.value
        if r == -1:
            raise EOFError("ring closed")
        return None

    def close_ring(self) -> None:
        self._lib.nns_ring_close(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.nns_ring_destroy(self._h)
            self._h = None


class RepoReader:
    """Background prefetching sample reader (gstdatareposrc.c redesign).

    A native thread preads samples (in the given order) into pooled aligned
    blocks; :meth:`next` hands back zero-copy numpy views. Call
    :meth:`release` when a sample's bytes have been consumed.
    """

    def __init__(self, path: str, sample_size: int, order: Sequence[int],
                 prefetch_depth: int = 8):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self.sample_size = sample_size
        # pool sized so the prefetcher can fill the ring while the consumer
        # holds a couple of blocks
        self._pool = BufferPool(sample_size, max_blocks=prefetch_depth + 4)
        order_arr = np.ascontiguousarray(order, dtype=np.uint64)
        self._h = lib.nns_repo_open(
            path.encode(), sample_size,
            order_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(order_arr), self._pool._h, prefetch_depth,
        )
        if not self._h:
            self._pool.close()
            raise OSError(f"cannot open {path}")

    def next(self, timeout_ms: int = -1):
        """Returns (numpy uint8 view, sample_index, block_ptr) or None on
        timeout; raises StopIteration at end of order; OSError on read error."""
        data = ctypes.c_void_p()
        idx = ctypes.c_uint64()
        r = self._lib.nns_repo_next(
            self._h, ctypes.byref(data), ctypes.byref(idx), timeout_ms,
        )
        if r == 1:
            return _as_numpy(data.value, self.sample_size), idx.value, data.value
        if r == -1:
            if self._lib.nns_repo_error(self._h):
                raise OSError("repo read error")
            raise StopIteration
        return None

    def release(self, block_ptr: int) -> None:
        self._lib.nns_repo_release(self._h, block_ptr)

    def cancel(self) -> None:
        """Unblock a consumer stuck in :meth:`next` (it sees StopIteration)
        without freeing native state; call before joining that consumer."""
        if self._h:
            self._lib.nns_repo_cancel(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.nns_repo_close(self._h)
            self._h = None
            self._pool.close()

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def gather(parts: List[np.ndarray], out: Optional[np.ndarray] = None) -> np.ndarray:
    """Concatenate byte views via one native memcpy pass (honors the
    ``NNS_DISABLE_NATIVE`` kill switch via :func:`available`)."""
    sizes = [p.nbytes for p in parts]
    total = sum(sizes)
    if out is None:
        out = np.empty(total, np.uint8)
    elif out.nbytes < total:
        raise ValueError(f"gather out buffer too small ({out.nbytes} < {total})")
    if not available():
        off = 0
        for p, s in zip(parts, sizes):
            out[off:off + s] = np.frombuffer(
                np.ascontiguousarray(p).data, np.uint8, s)
            off += s
        return out
    n = len(parts)
    contig = [np.ascontiguousarray(p) for p in parts]
    ptrs = (ctypes.c_void_p * n)(*(p.ctypes.data for p in contig))
    szs = (ctypes.c_uint64 * n)(*sizes)
    _lib.nns_memcpy_gather(out.ctypes.data, ptrs, szs, n)
    return out


def scatter(src: np.ndarray, outs: List[np.ndarray]) -> None:
    """Split a contiguous byte buffer into the given arrays natively."""
    src = np.ascontiguousarray(src)
    need = sum(o.nbytes for o in outs)
    if need > src.nbytes:
        raise ValueError(f"scatter source too small ({src.nbytes} < {need})")
    if not available():
        off = 0
        for o in outs:
            flat = o.reshape(-1).view(np.uint8)
            flat[:] = src[off:off + o.nbytes]
            off += o.nbytes
        return
    n = len(outs)
    ptrs = (ctypes.c_void_p * n)(*(o.ctypes.data for o in outs))
    szs = (ctypes.c_uint64 * n)(*(o.nbytes for o in outs))
    _lib.nns_memcpy_scatter(src.ctypes.data, ptrs, szs, n)
