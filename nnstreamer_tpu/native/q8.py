"""ctypes binding for the native int8 engine (``csrc/nns_q8.cc``).

Build-on-demand into ``libnns_q8.so`` (same atomic-publish pattern as the
host-runtime core in ``__init__.py``). The engine is the CPU-side analog
of the reference's native int8 interpreter path
(ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc); see the
.cc header comment for the arithmetic contract it shares with
``models/tflite_int8.py``.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

import numpy as np

from ._build import load_once

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libnns_q8.so")
_SRC = os.path.join(_HERE, "csrc", "nns_q8.cc")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False

ABI_VERSION = 1

_i8p = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")


def _bind(lib: ctypes.CDLL) -> None:
    i32, i64, vp = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p
    f32 = ctypes.c_float
    lib.nns_q8_abi.restype = ctypes.c_uint64
    lib.nns_q8_simd.restype = i32
    lib.nns_q8_new.restype = vp
    lib.nns_q8_new.argtypes = [i32]
    lib.nns_q8_free.argtypes = [vp]
    lib.nns_q8_buf.restype = i32
    lib.nns_q8_buf.argtypes = [vp, i32, i64]
    lib.nns_q8_alias.restype = i32
    lib.nns_q8_alias.argtypes = [vp, i32, i32]
    lib.nns_q8_io.restype = i32
    lib.nns_q8_io.argtypes = [vp, _i32p, i32, _i32p, i32]
    lib.nns_q8_add_conv.restype = i32
    lib.nns_q8_add_conv.argtypes = [vp] + [i32] * 15 + [
        _i8p, _i32p, _i32p, _f32p] + [i32] * 4
    lib.nns_q8_add_dw.restype = i32
    lib.nns_q8_add_dw.argtypes = [vp] + [i32] * 14 + [
        _i8p, _i32p, _i32p, _f32p] + [i32] * 4
    lib.nns_q8_add_add.restype = i32
    lib.nns_q8_add_add.argtypes = [vp, i32, i32, i32, i64, f32, f32, f32,
                                   i32, i32]
    lib.nns_q8_add_avgpool.restype = i32
    lib.nns_q8_add_avgpool.argtypes = [vp] + [i32] * 15 + [f32] + [i32] * 3
    lib.nns_q8_add_softmax.restype = i32
    lib.nns_q8_add_softmax.argtypes = [vp, i32, i32, i32, i32, f32, i32, f32,
                                       i32, f32]
    lib.nns_q8_run.restype = i32
    lib.nns_q8_run.argtypes = [vp, ctypes.POINTER(vp), ctypes.POINTER(vp)]


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        lib = load_once(_SRC, _LIB_PATH, ABI_VERSION, "nns_q8_abi", _bind)
        if lib is None:
            _build_failed = True
            return None
        _lib = lib
        return _lib


def available() -> bool:
    if os.environ.get("NNS_DISABLE_NATIVE"):
        return False
    return _load() is not None


def simd_level() -> int:
    """0 = portable scalar, 1 = AVX512-VNNI."""
    lib = _load()
    return int(lib.nns_q8_simd()) if lib is not None else -1


class Q8Program:
    """A built native program: fixed graph, reusable across frames.

    All quantization arguments are in the engine's stored domains (see
    nns_q8.cc): activations u8 (+128 biased for int8 tensors), weights
    s8, zero points likewise.
    """

    def __init__(self, n_bufs: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("q8 native engine unavailable")
        self._lib = lib
        self._h = lib.nns_q8_new(n_bufs)

    def buf(self, idx: int, nbytes: int) -> None:
        if self._lib.nns_q8_buf(self._h, idx, nbytes) != 0:
            raise ValueError(f"q8: bad buffer index {idx}")

    def alias(self, idx: int, src: int) -> None:
        if self._lib.nns_q8_alias(self._h, idx, src) != 0:
            raise ValueError(f"q8: bad alias {idx}->{src}")

    def io(self, ins: List[int], outs: List[int]) -> None:
        self._lib.nns_q8_io(
            self._h, np.asarray(ins, np.int32), len(ins),
            np.asarray(outs, np.int32), len(outs))

    def add_conv(self, in_idx, out_idx, n, h, w, c, oh, ow, oc, kh, kw, sh,
                 sw, pt, pl, wkn, wzp, bias, mult, xzp, yzp, lo, hi) -> None:
        wkn = np.ascontiguousarray(wkn, np.int8)
        wzp = np.ascontiguousarray(wzp, np.int32)
        bias = np.ascontiguousarray(
            bias if bias is not None else np.zeros(oc, np.int32), np.int32)
        mult = np.ascontiguousarray(mult, np.float32)
        r = self._lib.nns_q8_add_conv(
            self._h, in_idx, out_idx, n, h, w, c, oh, ow, oc, kh, kw, sh, sw,
            pt, pl, wkn, wzp, bias, mult, xzp, yzp, lo, hi)
        if r != 0:
            raise ValueError("q8: add_conv failed")

    def add_dw(self, in_idx, out_idx, n, h, w, c, oh, ow, kh, kw, sh, sw, pt,
               pl, w8, wzp, bias, mult, xzp, yzp, lo, hi) -> None:
        w8 = np.ascontiguousarray(w8, np.int8)
        wzp = np.ascontiguousarray(wzp, np.int32)
        bias = np.ascontiguousarray(
            bias if bias is not None else np.zeros(c, np.int32), np.int32)
        mult = np.ascontiguousarray(mult, np.float32)
        r = self._lib.nns_q8_add_dw(
            self._h, in_idx, out_idx, n, h, w, c, oh, ow, kh, kw, sh, sw, pt,
            pl, w8, wzp, bias, mult, xzp, yzp, lo, hi)
        if r != 0:
            raise ValueError("q8: add_dw failed")

    def add_add(self, a, b, out, elems, ka, kb, c0, lo, hi) -> None:
        self._lib.nns_q8_add_add(self._h, a, b, out, elems, ka, kb, c0, lo, hi)

    def add_avgpool(self, in_idx, out_idx, n, h, w, c, oh, ow, kh, kw, sh, sw,
                    pt, pl, xzp, ratio, yzp, lo, hi) -> None:
        self._lib.nns_q8_add_avgpool(
            self._h, in_idx, out_idx, n, h, w, c, oh, ow, kh, kw, sh, sw, pt,
            pl, xzp, ratio, yzp, lo, hi)

    def add_softmax(self, in_idx, out_idx, rows, cols, s_in, xzp, inv_s_out,
                    yzp, beta) -> None:
        self._lib.nns_q8_add_softmax(self._h, in_idx, out_idx, rows, cols,
                                     s_in, xzp, inv_s_out, yzp, beta)

    def run(self, inputs: List[np.ndarray], outputs: List[np.ndarray]) -> None:
        n_in, n_out = len(inputs), len(outputs)
        in_ptrs = (ctypes.c_void_p * n_in)(
            *(x.ctypes.data for x in inputs))
        out_ptrs = (ctypes.c_void_p * n_out)(
            *(x.ctypes.data for x in outputs))
        if self._lib.nns_q8_run(self._h, in_ptrs, out_ptrs) != 0:
            raise RuntimeError("q8: run failed")

    def close(self) -> None:
        if self._h:
            self._lib.nns_q8_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self.close()
        except Exception:
            pass
