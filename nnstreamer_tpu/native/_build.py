"""Shared build-on-demand loader for the native (C++) libraries.

One implementation of the compile/atomic-publish/mtime-rebuild/ABI-check
sequence, used by both ``libnns_core.so`` (``__init__.py``) and
``libnns_q8.so`` (``q8.py``). Concurrent processes may race to build;
building to a temp path and ``os.replace``-publishing keeps every reader
consistent. Callers keep their own per-module cache + failure latch and
call :func:`load_once` under their own lock.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Callable, Optional, Sequence

from ..utils.log import logger


def build(src: str, lib_path: str, extra_args: Sequence[str] = (),
          timeout: float = 180.0) -> bool:
    tmp = f"{lib_path}.{os.getpid()}.tmp"
    cmd = [
        os.environ.get("CXX", "g++"), "-O3", "-std=c++17", "-fPIC",
        "-shared", "-Wall", "-fvisibility=hidden", "-o", tmp, src,
        *extra_args,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            logger.warning("native build failed (%s):\n%s",
                           os.path.basename(src), proc.stderr)
            return False
        os.replace(tmp, lib_path)
        return True
    except (OSError, subprocess.TimeoutExpired) as e:  # g++ missing/hung
        logger.warning("native build unavailable (%s): %s",
                       os.path.basename(src), e)
        return False
    finally:
        # a failed/killed compile leaves its partial -o output behind;
        # one stranded .tmp per rebuild attempt adds up in shared caches
        try:
            os.remove(tmp)
        except OSError:
            pass


def load_once(src: str, lib_path: str, abi_version: int, abi_symbol: str,
              bind: Callable[[ctypes.CDLL], None],
              extra_args: Sequence[str] = ()) -> Optional[ctypes.CDLL]:
    """Build (if stale/missing), dlopen, ABI-check, and bind. Returns the
    bound library or None; the caller latches the failure."""
    if not os.path.exists(lib_path) or (
        os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(lib_path)
    ):
        if not build(src, lib_path, extra_args):
            return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError as e:
        logger.warning("native load failed (%s): %s",
                       os.path.basename(lib_path), e)
        return None
    abi_fn = getattr(lib, abi_symbol)
    abi_fn.restype = ctypes.c_uint64
    if abi_fn() != abi_version:
        # rebuild so the NEXT process gets a good library, but don't
        # re-dlopen here: glibc dedups by pathname and would hand back
        # the stale mapping — fail native for this process instead
        logger.warning("native ABI mismatch (%s); rebuilding and disabling "
                       "for this process", os.path.basename(lib_path))
        os.unlink(lib_path)
        build(src, lib_path, extra_args)
        return None
    bind(lib)
    return lib
