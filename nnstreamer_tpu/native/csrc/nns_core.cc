// Native host-runtime core for nnstreamer_tpu.
//
// Reference analogs (all C in the reference tree):
//   * aligned buffer pool  <- gst/nnstreamer/tensor_allocator.c (custom
//     GstAllocator with forced alignment) + GstBufferPool reuse semantics.
//   * SPSC ring            <- GStreamer `queue` element's bounded GQueue —
//     the reference's only stage-parallelism primitive (SURVEY.md §3.2).
//   * repo prefetch reader <- gst/datarepo/gstdatareposrc.c sample reads;
//     redesigned: a native reader thread preads samples ahead of the
//     pipeline into pooled aligned blocks so Python (GIL-bound) never
//     blocks on disk I/O — double-buffered host staging for the TPU feed.
//
// C ABI only (consumed via ctypes). No Python.h dependency: the boundary
// passes raw pointers + sizes; Python wraps them as numpy arrays.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC (see Makefile).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

#define NNS_API extern "C" __attribute__((visibility("default")))

namespace {

constexpr size_t kDefaultAlign = 64;  // cacheline; DMA-friendly

// ---------------------------------------------------------------------------
// Aligned buffer pool
// ---------------------------------------------------------------------------

struct Pool {
  size_t block_size;
  size_t alignment;
  std::mutex mu;
  std::vector<void *> free_list;   // blocks ready for reuse
  std::vector<void *> all_blocks;  // everything we ever allocated
  size_t max_blocks;               // 0 = unbounded growth
  std::atomic<uint64_t> acquires{0};
  std::atomic<uint64_t> reuses{0};

  ~Pool() {
    for (void *p : all_blocks) std::free(p);
  }
};

void *aligned_block(size_t size, size_t alignment) {
  void *p = nullptr;
  size_t rounded = (size + alignment - 1) / alignment * alignment;
  if (posix_memalign(&p, alignment, rounded) != 0) return nullptr;
  return p;
}

// ---------------------------------------------------------------------------
// SPSC ring of {data, size, tag} records
// ---------------------------------------------------------------------------

struct RingSlot {
  void *data;
  uint64_t size;
  uint64_t tag;
};

struct Ring {
  explicit Ring(size_t capacity) : slots(capacity + 1) {}
  std::vector<RingSlot> slots;  // one slot kept empty to distinguish full/empty
  std::atomic<size_t> head{0};  // consumer position
  std::atomic<size_t> tail{0};  // producer position
  std::mutex mu;                // only for the blocking waits
  std::condition_variable cv_put, cv_get;
  std::atomic<bool> closed{false};

  size_t next(size_t i) const { return (i + 1) % slots.size(); }

  bool push(const RingSlot &s, int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu);
    auto full = [&] { return next(tail.load()) == head.load(); };
    if (full()) {
      auto pred = [&] { return !full() || closed.load(); };
      if (timeout_ms < 0) {
        cv_put.wait(lk, pred);
      } else if (!cv_put.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred)) {
        return false;
      }
    }
    if (closed.load()) return false;
    slots[tail.load()] = s;
    tail.store(next(tail.load()));
    cv_get.notify_one();
    return true;
  }

  // returns: 1 popped, 0 timeout, -1 closed-and-drained
  int pop(RingSlot *out, int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu);
    auto empty = [&] { return head.load() == tail.load(); };
    if (empty()) {
      auto pred = [&] { return !empty() || closed.load(); };
      if (timeout_ms < 0) {
        cv_get.wait(lk, pred);
      } else if (!cv_get.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred)) {
        return 0;
      }
    }
    if (empty()) return closed.load() ? -1 : 0;
    *out = slots[head.load()];
    head.store(next(head.load()));
    cv_put.notify_one();
    return 1;
  }

  void close() {
    std::lock_guard<std::mutex> lk(mu);
    closed.store(true);
    cv_put.notify_all();
    cv_get.notify_all();
  }
};

// ---------------------------------------------------------------------------
// Datarepo prefetch reader
// ---------------------------------------------------------------------------

struct RepoReader {
  int fd = -1;
  size_t sample_size = 0;
  std::vector<uint64_t> order;  // sample indices, in emission order
  Pool *pool = nullptr;         // borrowed, not owned
  Ring ring;
  std::thread worker;
  std::atomic<bool> stop_flag{false};
  std::atomic<int> error{0};

  explicit RepoReader(size_t depth) : ring(depth) {}
};

}  // namespace

// ---------------------------------------------------------------------------
// Pool C ABI
// ---------------------------------------------------------------------------

NNS_API void *nns_pool_create(uint64_t block_size, uint64_t alignment,
                              uint64_t max_blocks) {
  auto *p = new Pool();
  p->block_size = block_size;
  p->alignment = alignment ? alignment : kDefaultAlign;
  p->max_blocks = max_blocks;
  return p;
}

NNS_API void *nns_pool_acquire(void *pool) {
  auto *p = static_cast<Pool *>(pool);
  p->acquires.fetch_add(1);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    if (!p->free_list.empty()) {
      void *b = p->free_list.back();
      p->free_list.pop_back();
      p->reuses.fetch_add(1);
      return b;
    }
    if (p->max_blocks && p->all_blocks.size() >= p->max_blocks) return nullptr;
  }
  void *b = aligned_block(p->block_size, p->alignment);
  if (b) {
    std::lock_guard<std::mutex> lk(p->mu);
    p->all_blocks.push_back(b);
  }
  return b;
}

NNS_API void nns_pool_release(void *pool, void *block) {
  auto *p = static_cast<Pool *>(pool);
  std::lock_guard<std::mutex> lk(p->mu);
  p->free_list.push_back(block);
}

NNS_API uint64_t nns_pool_stats(void *pool, uint64_t *reuses) {
  auto *p = static_cast<Pool *>(pool);
  if (reuses) *reuses = p->reuses.load();
  return p->acquires.load();
}

NNS_API void nns_pool_destroy(void *pool) { delete static_cast<Pool *>(pool); }

// ---------------------------------------------------------------------------
// Ring C ABI
// ---------------------------------------------------------------------------

NNS_API void *nns_ring_create(uint64_t capacity) { return new Ring(capacity); }

NNS_API int nns_ring_push(void *ring, void *data, uint64_t size, uint64_t tag,
                          int64_t timeout_ms) {
  return static_cast<Ring *>(ring)->push({data, size, tag}, timeout_ms) ? 1 : 0;
}

NNS_API int nns_ring_pop(void *ring, void **data, uint64_t *size, uint64_t *tag,
                         int64_t timeout_ms) {
  RingSlot s;
  int r = static_cast<Ring *>(ring)->pop(&s, timeout_ms);
  if (r == 1) {
    *data = s.data;
    *size = s.size;
    *tag = s.tag;
  }
  return r;
}

NNS_API void nns_ring_close(void *ring) { static_cast<Ring *>(ring)->close(); }

NNS_API void nns_ring_destroy(void *ring) { delete static_cast<Ring *>(ring); }

// ---------------------------------------------------------------------------
// Gather / scatter memcpy helpers (multi-tensor frame <-> contiguous wire
// payload without Python-level byte joins)
// ---------------------------------------------------------------------------

NNS_API void nns_memcpy_gather(void *dst, void **parts, uint64_t *sizes,
                               uint64_t n) {
  char *out = static_cast<char *>(dst);
  for (uint64_t i = 0; i < n; ++i) {
    std::memcpy(out, parts[i], sizes[i]);
    out += sizes[i];
  }
}

NNS_API void nns_memcpy_scatter(void *src, void **parts, uint64_t *sizes,
                                uint64_t n) {
  const char *in = static_cast<const char *>(src);
  for (uint64_t i = 0; i < n; ++i) {
    std::memcpy(parts[i], in, sizes[i]);
    in += sizes[i];
  }
}

// ---------------------------------------------------------------------------
// Repo prefetch reader C ABI
// ---------------------------------------------------------------------------

NNS_API void *nns_repo_open(const char *path, uint64_t sample_size,
                            const uint64_t *order, uint64_t n_order,
                            void *pool, uint64_t prefetch_depth) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  auto *r = new RepoReader(prefetch_depth ? prefetch_depth : 4);
  r->fd = fd;
  r->sample_size = sample_size;
  r->order.assign(order, order + n_order);
  r->pool = static_cast<Pool *>(pool);

  r->worker = std::thread([r] {
    for (uint64_t idx : r->order) {
      if (r->stop_flag.load()) break;
      void *block = nns_pool_acquire(r->pool);
      while (block == nullptr && !r->stop_flag.load()) {
        // pool exhausted (consumer owns all blocks): brief backoff
        std::this_thread::sleep_for(std::chrono::microseconds(200));
        block = nns_pool_acquire(r->pool);
      }
      if (block == nullptr) break;
      size_t done = 0;
      off_t base = static_cast<off_t>(idx) * r->sample_size;
      bool ok = true;
      while (done < r->sample_size) {
        ssize_t got = ::pread(r->fd, static_cast<char *>(block) + done,
                              r->sample_size - done, base + done);
        if (got <= 0) {
          ok = false;
          break;
        }
        done += got;
      }
      if (!ok) {
        nns_pool_release(r->pool, block);
        r->error.store(1);
        break;
      }
      if (!r->ring.push({block, r->sample_size, idx}, -1)) {
        nns_pool_release(r->pool, block);
        break;
      }
    }
    r->ring.close();
  });
  return r;
}

// returns 1 (sample ready), 0 (timeout), -1 (end of order / error; check
// nns_repo_error)
NNS_API int nns_repo_next(void *reader, void **data, uint64_t *idx,
                          int64_t timeout_ms) {
  auto *r = static_cast<RepoReader *>(reader);
  RingSlot s;
  int got = r->ring.pop(&s, timeout_ms);
  if (got == 1) {
    *data = s.data;
    *idx = s.tag;
  }
  return got;
}

NNS_API void nns_repo_release(void *reader, void *block) {
  auto *r = static_cast<RepoReader *>(reader);
  nns_pool_release(r->pool, block);
}

NNS_API int nns_repo_error(void *reader) {
  return static_cast<RepoReader *>(reader)->error.load();
}

// Unblock both sides (producer + a consumer stuck in nns_repo_next) without
// freeing anything. Safe to call from a thread other than the consumer;
// the consumer sees end-of-stream on its next pop. Call before join/close.
NNS_API void nns_repo_cancel(void *reader) {
  auto *r = static_cast<RepoReader *>(reader);
  r->stop_flag.store(true);
  r->ring.close();
}

NNS_API void nns_repo_close(void *reader) {
  auto *r = static_cast<RepoReader *>(reader);
  r->stop_flag.store(true);
  r->ring.close();
  // drain anything the worker already queued so blocks return to the pool
  RingSlot s;
  while (r->ring.pop(&s, 0) == 1) nns_pool_release(r->pool, s.data);
  if (r->worker.joinable()) r->worker.join();
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

NNS_API uint64_t nns_abi_version() { return 1; }
