/* nns_custom_filter.h — C ABI for user-written custom filter plugins.
 *
 * Reference analog: the raw-C custom filter interface of
 * gst/nnstreamer/tensor_filter/tensor_filter_custom.h (NNStreamer_custom_class:
 * init/exit/getInputDim/getOutputDim/setInputDim/invoke). Redesigned as a
 * plain-C symbol ABI (no GLib types): a plugin is any shared object exporting
 * the nns_custom_* symbols below; the Python pipeline loads it with
 *     tensor_filter framework=custom model=/path/libmyfilter.so custom=opts
 * through ctypes (backends/custom_c.py).
 *
 * Contract:
 *  - All functions are called from one pipeline thread at a time per handle.
 *  - Output buffers are allocated by the CALLER from the plugin's declared
 *    output spec; invoke() writes results in place (no plugin-side malloc
 *    crossing the boundary, unlike the reference's allocate_in_invoke).
 *  - Return 0 for success, negative for failure.
 */
#ifndef NNS_CUSTOM_FILTER_H
#define NNS_CUSTOM_FILTER_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define NNS_CUSTOM_ABI_VERSION 1
#define NNS_MAX_TENSORS 16
#define NNS_MAX_RANK 8

/* dtype codes (order matches nnstreamer_tpu.core.DataType) */
typedef enum {
  NNS_INT8 = 0,
  NNS_UINT8 = 1,
  NNS_INT16 = 2,
  NNS_UINT16 = 3,
  NNS_INT32 = 4,
  NNS_UINT32 = 5,
  NNS_INT64 = 6,
  NNS_UINT64 = 7,
  NNS_FLOAT16 = 8,
  NNS_FLOAT32 = 9,
  NNS_FLOAT64 = 10,
  NNS_BFLOAT16 = 11,
  NNS_BOOL = 12,
} nns_dtype;

typedef struct {
  int32_t dtype;  /* nns_dtype */
  int32_t rank;
  int64_t dims[NNS_MAX_RANK];
} nns_tensor_spec;

typedef struct {
  uint32_t num;
  nns_tensor_spec spec[NNS_MAX_TENSORS];
} nns_tensors_spec;

typedef struct {
  void *data;     /* const for inputs; caller-allocated for outputs */
  uint64_t size;  /* bytes */
} nns_tensor_view;

/* -- required exports ---------------------------------------------------- */

/* ABI version of the plugin; loader rejects mismatches. */
int32_t nns_custom_abi_version(void);

/* Create one filter instance. options = the element's custom= string (may be
 * empty, never NULL). Return NULL on failure. */
void *nns_custom_open(const char *options);

void nns_custom_close(void *handle);

/* Run one frame. in/out views are parallel to the negotiated specs. */
int nns_custom_invoke(void *handle, const nns_tensor_view *in, uint32_t n_in,
                      nns_tensor_view *out, uint32_t n_out);

/* -- optional exports (at least ONE of the two must be present) ---------- */

/* Static-shape plugins: declare both specs. Return 0 on success. */
int nns_custom_get_info(void *handle, nns_tensors_spec *in_spec,
                        nns_tensors_spec *out_spec);

/* Dynamic-shape plugins: given the negotiated input spec, fill the output
 * spec (reference setInputDimension). Return 0 on success. */
int nns_custom_set_input(void *handle, const nns_tensors_spec *in_spec,
                         nns_tensors_spec *out_spec);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* NNS_CUSTOM_FILTER_H */
