// Native int8 inference engine for quantized tflite imports.
//
// Role in the framework: the CPU-side analog of the reference's native
// int8 interpreter path (ext/nnstreamer/tensor_filter/
// tensor_filter_tensorflow_lite.cc runs XNNPACK's int8 kernels). Our
// XLA int8 executor (models/tflite_int8.py) already beats the
// interpreter's GEMMs, but XLA-CPU cannot fuse the requantize epilogue
// into the GEMM library call — each layer pays an extra int32
// materialization + elementwise pass (measured ~0.3-0.8 ms/layer on the
// big early-network activations; PERF_PROFILE_r05.md). This engine
// closes exactly that gap: the requantize (per-channel scale, round,
// zero-point add, clamp, int8 pack) happens in registers inside the
// GEMM epilogue, so each activation is written once, as int8.
//
// Arithmetic contract (identical to models/tflite_int8.py, so the two
// paths cross-check byte-for-byte):
//   * activations are carried in an unsigned-u8 stored domain (int8
//     tensors are biased +128 by the caller; zero points likewise),
//   * weights are signed-s8 (uint8 weights biased -128) — the
//     AVX512-VNNI vpdpbusd instruction multiplies u8 x s8 into i32,
//   * conv = im2col + GEMM with exact int32 accumulators; zero-point
//     cross terms folded into a per-channel constant plus (when the
//     weight zero point is nonzero) a per-row activation-sum term,
//   * depthwise runs as f32 FMAs over zero-point-folded weights —
//     integer-exact (all products < 2^24),
//   * requantize: f32 multiply by (s_in*s_w/s_out), round-to-nearest-
//     EVEN (matches jnp.round and _mm512_cvtps_epi32's default mode),
//     add output zero point, clamp to the fused-activation range.
//
// SIMD dispatch is at runtime (function target attributes +
// __builtin_cpu_supports), with plain-C++ fallbacks: the .so loads and
// runs on any x86-64; VNNI is used when the host has it. Threading:
// none — the engine is single-threaded by design; parallelism belongs
// to the pipeline layer (one element = one streaming thread), exactly
// as in the reference's design.
#include <immintrin.h>

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint64_t kAbi = 1;

struct Buf {
  std::vector<uint8_t> data;
  int alias_of = -1;
  int64_t nbytes = 0;
};

enum class OpK { Conv, Dw, Add, AvgPool, Softmax };

struct Op {
  OpK k;
  int in = 0, in2 = 0, out = 0;
  // geometry (conv/dw/pool): input n,h,w,c -> oh,ow,oc
  int n = 1, h = 0, w = 0, c = 0, oh = 0, ow = 0, oc = 0;
  int kh = 1, kw = 1, sh = 1, sw = 1, pt = 0, pl = 0, pb = 0, pr = 0;
  int K = 0, K4 = 0, ocp = 0;  // GEMM dims (K4 = K rounded to 4, ocp to 16)
  bool direct_a = false;       // 1x1 stride-1 conv: A = input, no im2col
  int need_rowsum = 0;
  std::vector<int8_t> wpack;   // GEMM B, packed [oc16-block][K4/4][16][4]
  std::vector<float> wf;       // dw weights, zero-point folded [kh*kw][c16]
  std::vector<int32_t> bias_eff;  // conv: per-channel constant (ocp)
  std::vector<float> biasf;       // dw: folded bias (c16)
  std::vector<float> mult;        // requant multiplier (ocp / c16)
  std::vector<int32_t> wzp;       // s8-domain weight zero points (ocp)
  int xzp = 0, yzp = 0, lo = 0, hi = 255;  // u8 stored domain
  // add
  int64_t elems = 0;
  float ka = 0.f, kb = 0.f, c0 = 0.f;
  // avgpool
  float ratio = 1.f;
  // softmax
  int rows = 0, cols = 0;
  float s_in = 0.f, inv_s_out = 0.f, beta = 1.f;
};

struct Prog {
  std::vector<Buf> bufs;
  std::vector<Op> ops;
  std::vector<int> ins, outs;
  std::vector<uint8_t> scratch_a;   // im2col patch matrix
  std::vector<uint8_t> scratch_pad; // padded input (dw)
  std::vector<int32_t> rowsum;
  int simd = -1;  // resolved at first run
};

uint8_t *bptr(Prog *p, int idx) {
  int i = idx;
  while (p->bufs[i].alias_of >= 0) i = p->bufs[i].alias_of;
  return p->bufs[i].data.data();
}

inline int round_up(int v, int m) { return (v + m - 1) / m * m; }

int detect_simd() {
#if defined(__x86_64__) || defined(_M_X64)
  __builtin_cpu_init();
  if (__builtin_cpu_supports("avx512f") &&
      __builtin_cpu_supports("avx512bw") &&
      __builtin_cpu_supports("avx512vl") &&
      __builtin_cpu_supports("avx512vnni"))
    return 1;
#endif
  return 0;
}

// ---------------------------------------------------------------------------
// scalar reference kernels (portable fallback; also the documentation of
// the exact arithmetic — the SIMD kernels must match these bit-for-bit)
// ---------------------------------------------------------------------------

inline uint8_t requant_scalar(float acc, float mult, int yzp, int lo, int hi) {
  // lrintf honours the current rounding mode; processes run in the
  // default round-to-nearest-even, matching _mm512_cvtps_epi32
  int v = static_cast<int>(lrintf(acc * mult)) + yzp;
  v = std::min(std::max(v, lo), hi);
  return static_cast<uint8_t>(v);
}

void gemm_scalar(const uint8_t *A, int M, const Op &op, uint8_t *out,
                 const int32_t *rowsum) {
  const int K4 = op.K4, ocp = op.ocp, oc = op.oc;
  for (int m = 0; m < M; ++m) {
    const uint8_t *a = A + static_cast<int64_t>(m) * K4;
    for (int nb = 0; nb < ocp; nb += 16) {
      int32_t acc[16];
      for (int j = 0; j < 16; ++j) acc[j] = 0;
      for (int g = 0; g < K4 / 4; ++g) {
        // packed block layout: [oc16-block][K4/4][16][4]
        const int8_t *bq = op.wpack.data() +
                           (static_cast<int64_t>(nb / 16) * (K4 / 4) + g) * 64;
        for (int j = 0; j < 16; ++j)
          for (int t = 0; t < 4; ++t)
            acc[j] += static_cast<int32_t>(a[g * 4 + t]) *
                      static_cast<int32_t>(bq[j * 4 + t]);
      }
      for (int j = 0; j < 16; ++j) {
        int nch = nb + j;
        if (nch >= oc) break;
        int32_t v = acc[j] + op.bias_eff[nch];
        if (op.need_rowsum) v -= op.wzp[nch] * rowsum[m];
        out[static_cast<int64_t>(m) * oc + nch] = requant_scalar(
            static_cast<float>(v), op.mult[nch], op.yzp, op.lo, op.hi);
      }
    }
  }
}

void dw_scalar(const uint8_t *xpad, const Op &op, uint8_t *out) {
  const int wp = op.w + op.pl + op.pr;
  const int c = op.c, c16 = round_up(c, 16);
  for (int y = 0; y < op.oh; ++y)
    for (int x = 0; x < op.ow; ++x)
      for (int ch = 0; ch < c; ++ch) {
        float acc = op.biasf[ch];
        for (int ky = 0; ky < op.kh; ++ky)
          for (int kx = 0; kx < op.kw; ++kx) {
            int iy = y * op.sh + ky, ix = x * op.sw + kx;
            float xv = static_cast<float>(
                xpad[(static_cast<int64_t>(iy) * wp + ix) * c + ch]);
            acc += xv * op.wf[(static_cast<int64_t>(ky) * op.kw + kx) * c16 + ch];
          }
        out[(static_cast<int64_t>(y) * op.ow + x) * c + ch] =
            requant_scalar(acc, op.mult[ch], op.yzp, op.lo, op.hi);
      }
}

void add_scalar(const uint8_t *a, const uint8_t *b, const Op &op, uint8_t *out) {
  for (int64_t i = 0; i < op.elems; ++i) {
    float y = static_cast<float>(a[i]) * op.ka +
              static_cast<float>(b[i]) * op.kb + op.c0;
    int v = static_cast<int>(lrintf(y));
    out[i] = static_cast<uint8_t>(std::min(std::max(v, op.lo), op.hi));
  }
}

void avgpool_scalar(const uint8_t *x, const Op &op, uint8_t *out) {
  for (int y = 0; y < op.oh; ++y)
    for (int xo = 0; xo < op.ow; ++xo) {
      int y0 = std::max(0, y * op.sh - op.pt);
      int x0 = std::max(0, xo * op.sw - op.pl);
      int y1 = std::min(op.h, y * op.sh - op.pt + op.kh);
      int x1 = std::min(op.w, xo * op.sw - op.pl + op.kw);
      int count = (y1 - y0) * (x1 - x0);
      float f = op.ratio / static_cast<float>(count);
      for (int ch = 0; ch < op.c; ++ch) {
        int32_t total = 0;
        for (int iy = y0; iy < y1; ++iy)
          for (int ix = x0; ix < x1; ++ix)
            total += x[(static_cast<int64_t>(iy) * op.w + ix) * op.c + ch];
        total -= count * op.xzp;
        int v = static_cast<int>(lrintf(static_cast<float>(total) * f)) + op.yzp;
        out[(static_cast<int64_t>(y) * op.ow + xo) * op.c + ch] =
            static_cast<uint8_t>(std::min(std::max(v, op.lo), op.hi));
      }
    }
}

void softmax_scalar(const uint8_t *x, const Op &op, uint8_t *out) {
  std::vector<float> f(op.cols);
  for (int r = 0; r < op.rows; ++r) {
    const uint8_t *xr = x + static_cast<int64_t>(r) * op.cols;
    uint8_t *yr = out + static_cast<int64_t>(r) * op.cols;
    float mx = -1e30f;
    for (int j = 0; j < op.cols; ++j) {
      f[j] = (static_cast<float>(xr[j]) - op.xzp) * op.s_in * op.beta;
      mx = std::max(mx, f[j]);
    }
    float sum = 0.f;
    for (int j = 0; j < op.cols; ++j) {
      f[j] = expf(f[j] - mx);
      sum += f[j];
    }
    for (int j = 0; j < op.cols; ++j) {
      float y = f[j] / sum;
      int v = static_cast<int>(lrintf(y * op.inv_s_out)) + op.yzp;
      yr[j] = static_cast<uint8_t>(std::min(std::max(v, 0), 255));
    }
  }
}

// ---------------------------------------------------------------------------
// AVX512-VNNI kernels
// ---------------------------------------------------------------------------
#if defined(__x86_64__) || defined(_M_X64)

__attribute__((target("avx512f,avx512bw,avx512vl,avx512vnni")))
void rowsum_vnni(const uint8_t *A, int M, int K4, int32_t *rowsum) {
  for (int m = 0; m < M; ++m) {
    const uint8_t *a = A + static_cast<int64_t>(m) * K4;
    __m512i acc = _mm512_setzero_si512();
    int k = 0;
    for (; k + 64 <= K4; k += 64) {
      __m512i v = _mm512_loadu_si512(a + k);
      acc = _mm512_add_epi64(acc, _mm512_sad_epu8(v, _mm512_setzero_si512()));
    }
    if (k < K4) {
      __mmask64 mask = (~0ULL) >> (64 - (K4 - k));
      __m512i v = _mm512_maskz_loadu_epi8(mask, a + k);
      acc = _mm512_add_epi64(acc, _mm512_sad_epu8(v, _mm512_setzero_si512()));
    }
    rowsum[m] = static_cast<int32_t>(_mm512_reduce_add_epi64(acc));
  }
}

// requant 16 int32 lanes -> up to 16 u8 bytes (masked store)
__attribute__((target("avx512f,avx512bw,avx512vl,avx512vnni")))
inline void requant_store16(__m512i acc, const float *mult, int yzp, int lo,
                            int hi, uint8_t *dst, __mmask16 mask) {
  __m512 f = _mm512_mul_ps(_mm512_cvtepi32_ps(acc), _mm512_loadu_ps(mult));
  __m512i i = _mm512_add_epi32(_mm512_cvtps_epi32(f), _mm512_set1_epi32(yzp));
  i = _mm512_max_epi32(i, _mm512_set1_epi32(lo));
  i = _mm512_min_epi32(i, _mm512_set1_epi32(hi));
  _mm_mask_storeu_epi8(dst, mask, _mm512_cvtepi32_epi8(i));
}

__attribute__((target("avx512f,avx512bw,avx512vl,avx512vnni")))
void gemm_vnni(const uint8_t *A, int M, const Op &op, uint8_t *out,
               const int32_t *rowsum) {
  const int K4 = op.K4, ocp = op.ocp, oc = op.oc, groups = K4 / 4;
  const int nblocks = ocp / 16;
  for (int m0 = 0; m0 < M; m0 += 4) {
    const int mr = std::min(4, M - m0);
    // tail rows recompute row m0 (stores are gated on mr)
    const uint8_t *a0 = A + static_cast<int64_t>(m0) * K4;
    const uint8_t *a1 = A + static_cast<int64_t>(m0 + (mr > 1 ? 1 : 0)) * K4;
    const uint8_t *a2 = A + static_cast<int64_t>(m0 + (mr > 2 ? 2 : 0)) * K4;
    const uint8_t *a3 = A + static_cast<int64_t>(m0 + (mr > 3 ? 3 : 0)) * K4;
    for (int nb = 0; nb < nblocks; ++nb) {
      const int8_t *bq = op.wpack.data() +
                         static_cast<int64_t>(nb) * groups * 64;
      __m512i acc0 = _mm512_setzero_si512();
      __m512i acc1 = _mm512_setzero_si512();
      __m512i acc2 = _mm512_setzero_si512();
      __m512i acc3 = _mm512_setzero_si512();
      for (int g = 0; g < groups; ++g) {
        const __m512i b = _mm512_loadu_si512(bq + static_cast<int64_t>(g) * 64);
        int32_t v0, v1, v2, v3;
        std::memcpy(&v0, a0 + g * 4, 4);
        std::memcpy(&v1, a1 + g * 4, 4);
        std::memcpy(&v2, a2 + g * 4, 4);
        std::memcpy(&v3, a3 + g * 4, 4);
        acc0 = _mm512_dpbusd_epi32(acc0, _mm512_set1_epi32(v0), b);
        acc1 = _mm512_dpbusd_epi32(acc1, _mm512_set1_epi32(v1), b);
        acc2 = _mm512_dpbusd_epi32(acc2, _mm512_set1_epi32(v2), b);
        acc3 = _mm512_dpbusd_epi32(acc3, _mm512_set1_epi32(v3), b);
      }
      const int nch = nb * 16;
      const int wn = std::min(16, oc - nch);
      if (wn <= 0) continue;  // fully padded trailing block
      const __mmask16 mask = static_cast<__mmask16>((1u << wn) - 1u);
      const __m512i bias = _mm512_loadu_si512(op.bias_eff.data() + nch);
      const __m512i wzp = op.need_rowsum
                              ? _mm512_loadu_si512(op.wzp.data() + nch)
                              : _mm512_setzero_si512();
      __m512i r[4] = {acc0, acc1, acc2, acc3};
      for (int t = 0; t < mr; ++t) {
        __m512i acc = _mm512_add_epi32(r[t], bias);
        if (op.need_rowsum)
          acc = _mm512_sub_epi32(
              acc, _mm512_mullo_epi32(wzp, _mm512_set1_epi32(rowsum[m0 + t])));
        requant_store16(acc, op.mult.data() + nch, op.yzp, op.lo, op.hi,
                        out + (static_cast<int64_t>(m0 + t)) * oc + nch, mask);
      }
    }
  }
}

__attribute__((target("avx512f,avx512bw,avx512vl,avx512vnni")))
void dw_vnni(const uint8_t *xpad, const Op &op, uint8_t *out) {
  const int wp = op.w + op.pl + op.pr;
  const int c = op.c, c16 = round_up(c, 16);
  const int taps = op.kh * op.kw;
  for (int y = 0; y < op.oh; ++y) {
    for (int x = 0; x < op.ow; ++x) {
      const int64_t ibase =
          (static_cast<int64_t>(y * op.sh) * wp + x * op.sw) * c;
      uint8_t *dst = out + (static_cast<int64_t>(y) * op.ow + x) * c;
      for (int cb = 0; cb < c; cb += 16) {
        const int wn = std::min(16, c - cb);
        const __mmask16 mask = static_cast<__mmask16>((1u << wn) - 1u);
        __m512 acc = _mm512_loadu_ps(op.biasf.data() + cb);
        for (int t = 0; t < taps; ++t) {
          const int ky = t / op.kw, kx = t % op.kw;
          const uint8_t *src =
              xpad + ibase + (static_cast<int64_t>(ky) * wp + kx) * c + cb;
          __m128i v8 = _mm_maskz_loadu_epi8(mask, src);
          __m512 xf = _mm512_cvtepi32_ps(_mm512_cvtepu8_epi32(v8));
          acc = _mm512_fmadd_ps(
              xf, _mm512_loadu_ps(op.wf.data() + static_cast<int64_t>(t) * c16 + cb),
              acc);
        }
        __m512 f = _mm512_mul_ps(acc, _mm512_loadu_ps(op.mult.data() + cb));
        __m512i i = _mm512_add_epi32(_mm512_cvtps_epi32(f),
                                     _mm512_set1_epi32(op.yzp));
        i = _mm512_max_epi32(i, _mm512_set1_epi32(op.lo));
        i = _mm512_min_epi32(i, _mm512_set1_epi32(op.hi));
        _mm_mask_storeu_epi8(dst + cb, mask, _mm512_cvtepi32_epi8(i));
      }
    }
  }
}

__attribute__((target("avx512f,avx512bw,avx512vl,avx512vnni")))
void add_vnni(const uint8_t *a, const uint8_t *b, const Op &op, uint8_t *out) {
  const __m512 ka = _mm512_set1_ps(op.ka), kb = _mm512_set1_ps(op.kb);
  const __m512 c0 = _mm512_set1_ps(op.c0);
  const __m512i lo = _mm512_set1_epi32(op.lo), hi = _mm512_set1_epi32(op.hi);
  int64_t i = 0;
  for (; i + 16 <= op.elems; i += 16) {
    __m512 af = _mm512_cvtepi32_ps(
        _mm512_cvtepu8_epi32(_mm_loadu_si128(
            reinterpret_cast<const __m128i *>(a + i))));
    __m512 bf = _mm512_cvtepi32_ps(
        _mm512_cvtepu8_epi32(_mm_loadu_si128(
            reinterpret_cast<const __m128i *>(b + i))));
    __m512 y = _mm512_fmadd_ps(af, ka, _mm512_fmadd_ps(bf, kb, c0));
    __m512i v = _mm512_cvtps_epi32(y);
    v = _mm512_min_epi32(_mm512_max_epi32(v, lo), hi);
    _mm_storeu_si128(reinterpret_cast<__m128i *>(out + i),
                     _mm512_cvtepi32_epi8(v));
  }
  if (i < op.elems) {
    const int rem = static_cast<int>(op.elems - i);
    const __mmask16 mask = static_cast<__mmask16>((1u << rem) - 1u);
    __m512 af = _mm512_cvtepi32_ps(
        _mm512_cvtepu8_epi32(_mm_maskz_loadu_epi8(mask, a + i)));
    __m512 bf = _mm512_cvtepi32_ps(
        _mm512_cvtepu8_epi32(_mm_maskz_loadu_epi8(mask, b + i)));
    __m512 y = _mm512_fmadd_ps(af, ka, _mm512_fmadd_ps(bf, kb, c0));
    __m512i v = _mm512_cvtps_epi32(y);
    v = _mm512_min_epi32(_mm512_max_epi32(v, lo), hi);
    _mm_mask_storeu_epi8(out + i, mask, _mm512_cvtepi32_epi8(v));
  }
}
#endif  // x86_64

// ---------------------------------------------------------------------------
// op execution
// ---------------------------------------------------------------------------

void pad_input(const uint8_t *x, const Op &op, uint8_t *xpad) {
  const int wp = op.w + op.pl + op.pr;
  const int hp = op.h + op.pt + op.pb;
  const int64_t rowb = static_cast<int64_t>(wp) * op.c;
  if (op.pt || op.pb || op.pl || op.pr)
    std::memset(xpad, static_cast<uint8_t>(op.xzp),
                static_cast<size_t>(hp) * rowb);
  for (int y = 0; y < op.h; ++y)
    std::memcpy(xpad + (static_cast<int64_t>(y + op.pt) * wp + op.pl) * op.c,
                x + static_cast<int64_t>(y) * op.w * op.c,
                static_cast<size_t>(op.w) * op.c);
}

// im2col: one patch row per output pixel, rows padded to K4 with xzp
void im2col(const uint8_t *x, const Op &op, uint8_t *A) {
  const int K4 = op.K4;
  const int64_t rowc = static_cast<int64_t>(op.w) * op.c;
  for (int y = 0; y < op.oh; ++y) {
    for (int xo = 0; xo < op.ow; ++xo) {
      uint8_t *dst = A + (static_cast<int64_t>(y) * op.ow + xo) * K4;
      int off = 0;
      for (int ky = 0; ky < op.kh; ++ky) {
        const int iy = y * op.sh + ky - op.pt;
        if (iy < 0 || iy >= op.h) {
          std::memset(dst + off, static_cast<uint8_t>(op.xzp),
                      static_cast<size_t>(op.kw) * op.c);
          off += op.kw * op.c;
          continue;
        }
        const int ix0 = xo * op.sw - op.pl;
        // contiguous fast path when the whole kx span is in-bounds
        if (ix0 >= 0 && ix0 + op.kw <= op.w) {
          std::memcpy(dst + off, x + iy * rowc + static_cast<int64_t>(ix0) * op.c,
                      static_cast<size_t>(op.kw) * op.c);
          off += op.kw * op.c;
        } else {
          for (int kx = 0; kx < op.kw; ++kx) {
            const int ix = ix0 + kx;
            if (ix < 0 || ix >= op.w)
              std::memset(dst + off, static_cast<uint8_t>(op.xzp), op.c);
            else
              std::memcpy(dst + off, x + iy * rowc + static_cast<int64_t>(ix) * op.c,
                          op.c);
            off += op.c;
          }
        }
      }
      if (off < K4)
        std::memset(dst + off, static_cast<uint8_t>(op.xzp), K4 - off);
    }
  }
}

void run_conv(Prog *p, const Op &op) {
  const uint8_t *x = bptr(p, op.in);
  uint8_t *out = bptr(p, op.out);
  const int M = op.oh * op.ow;
  const int64_t in_img = static_cast<int64_t>(op.h) * op.w * op.c;
  const int64_t out_img = static_cast<int64_t>(M) * op.oc;
  for (int img = 0; img < op.n; ++img) {
    const uint8_t *A;
    if (op.direct_a) {
      A = x + img * in_img;
    } else {
      im2col(x + img * in_img, op, p->scratch_a.data());
      A = p->scratch_a.data();
    }
    const int32_t *rs = nullptr;
    if (op.need_rowsum) {
#if defined(__x86_64__) || defined(_M_X64)
      if (p->simd == 1)
        rowsum_vnni(A, M, op.K4, p->rowsum.data());
      else
#endif
      {
        for (int m = 0; m < M; ++m) {
          const uint8_t *a = A + static_cast<int64_t>(m) * op.K4;
          int32_t s = 0;
          for (int k = 0; k < op.K4; ++k) s += a[k];
          p->rowsum[m] = s;
        }
      }
      rs = p->rowsum.data();
    }
#if defined(__x86_64__) || defined(_M_X64)
    if (p->simd == 1)
      gemm_vnni(A, M, op, out + img * out_img, rs);
    else
#endif
      gemm_scalar(A, M, op, out + img * out_img, rs);
  }
}

void run_dw(Prog *p, const Op &op) {
  const uint8_t *x = bptr(p, op.in);
  uint8_t *out = bptr(p, op.out);
  const int64_t in_img = static_cast<int64_t>(op.h) * op.w * op.c;
  const int64_t out_img = static_cast<int64_t>(op.oh) * op.ow * op.c;
  const bool padded = op.pt || op.pb || op.pl || op.pr;
  for (int img = 0; img < op.n; ++img) {
    const uint8_t *src;
    if (padded) {
      pad_input(x + img * in_img, op, p->scratch_pad.data());
      src = p->scratch_pad.data();
    } else {
      src = x + img * in_img;
    }
#if defined(__x86_64__) || defined(_M_X64)
    if (p->simd == 1)
      dw_vnni(src, op, out + img * out_img);
    else
#endif
      dw_scalar(src, op, out + img * out_img);
  }
}

void run_op(Prog *p, const Op &op) {
  switch (op.k) {
    case OpK::Conv:
      run_conv(p, op);
      break;
    case OpK::Dw:
      run_dw(p, op);
      break;
    case OpK::Add:
#if defined(__x86_64__) || defined(_M_X64)
      if (p->simd == 1) {
        add_vnni(bptr(p, op.in), bptr(p, op.in2), op, bptr(p, op.out));
        break;
      }
#endif
      add_scalar(bptr(p, op.in), bptr(p, op.in2), op, bptr(p, op.out));
      break;
    case OpK::AvgPool: {
      const uint8_t *x = bptr(p, op.in);
      uint8_t *out = bptr(p, op.out);
      const int64_t in_img = static_cast<int64_t>(op.h) * op.w * op.c;
      const int64_t out_img = static_cast<int64_t>(op.oh) * op.ow * op.c;
      for (int img = 0; img < op.n; ++img)
        avgpool_scalar(x + img * in_img, op, out + img * out_img);
      break;
    }
    case OpK::Softmax:
      softmax_scalar(bptr(p, op.in), op, bptr(p, op.out));
      break;
  }
}

// pack a [K][oc] s8 weight matrix into [oc16-block][K4/4][16][4]
void pack_b(const int8_t *wkn, int K, int oc, Op *op) {
  const int K4 = op->K4, ocp = op->ocp;
  op->wpack.assign(static_cast<size_t>(ocp) * K4, 0);
  for (int nb = 0; nb < ocp; nb += 16) {
    int8_t *blk = op->wpack.data() + static_cast<int64_t>(nb / 16) * (K4 / 4) * 64;
    for (int g = 0; g < K4 / 4; ++g)
      for (int j = 0; j < 16; ++j)
        for (int t = 0; t < 4; ++t) {
          const int k = g * 4 + t, nch = nb + j;
          blk[static_cast<int64_t>(g) * 64 + j * 4 + t] =
              (k < K && nch < oc) ? wkn[static_cast<int64_t>(k) * oc + nch]
                                  : static_cast<int8_t>(0);
        }
  }
}

}  // namespace

extern "C" {

__attribute__((visibility("default"))) uint64_t nns_q8_abi(void) { return kAbi; }

__attribute__((visibility("default"))) int nns_q8_simd(void) {
  return detect_simd();
}

__attribute__((visibility("default"))) void *nns_q8_new(int n_bufs) {
  Prog *p = new Prog();
  p->bufs.resize(n_bufs);
  return p;
}

__attribute__((visibility("default"))) void nns_q8_free(void *h) {
  delete static_cast<Prog *>(h);
}

__attribute__((visibility("default"))) int nns_q8_buf(void *h, int idx,
                                                      int64_t nbytes) {
  Prog *p = static_cast<Prog *>(h);
  if (idx < 0 || idx >= static_cast<int>(p->bufs.size())) return -1;
  p->bufs[idx].data.assign(static_cast<size_t>(nbytes), 0);
  p->bufs[idx].nbytes = nbytes;
  return 0;
}

__attribute__((visibility("default"))) int nns_q8_alias(void *h, int idx,
                                                        int src) {
  Prog *p = static_cast<Prog *>(h);
  if (idx < 0 || idx >= static_cast<int>(p->bufs.size())) return -1;
  p->bufs[idx].alias_of = src;
  p->bufs[idx].nbytes = p->bufs[src].nbytes;
  return 0;
}

__attribute__((visibility("default"))) int nns_q8_io(void *h, const int32_t *ins,
                                                     int n_in,
                                                     const int32_t *outs,
                                                     int n_out) {
  Prog *p = static_cast<Prog *>(h);
  p->ins.assign(ins, ins + n_in);
  p->outs.assign(outs, outs + n_out);
  return 0;
}

// weights arrive as stored bytes [kh][kw][c][oc] reordered by the caller
// to [K][oc] (K = kh*kw*c, patch order ky,kx,ic), already in the s8 domain
__attribute__((visibility("default"))) int nns_q8_add_conv(
    void *h, int in, int out, int n, int hgt, int wid, int c, int oh, int ow,
    int oc, int kh, int kw, int sh, int sw, int pt, int pl, const int8_t *wkn,
    const int32_t *wzp, const int32_t *bias, const float *mult, int xzp,
    int yzp, int lo, int hi) {
  Prog *p = static_cast<Prog *>(h);
  Op op;
  op.k = OpK::Conv;
  op.in = in;
  op.out = out;
  op.n = n;
  op.h = hgt;
  op.w = wid;
  op.c = c;
  op.oh = oh;
  op.ow = ow;
  op.oc = oc;
  op.kh = kh;
  op.kw = kw;
  op.sh = sh;
  op.sw = sw;
  op.pt = pt;
  op.pl = pl;
  op.xzp = xzp;
  op.yzp = yzp;
  op.lo = lo;
  op.hi = hi;
  op.K = kh * kw * c;
  op.K4 = round_up(op.K, 4);
  op.ocp = round_up(oc, 16);
  op.direct_a = (kh == 1 && kw == 1 && sh == 1 && sw == 1 && pt == 0 &&
                 pl == 0 && c % 4 == 0 && oh == hgt && ow == wid);
  pack_b(wkn, op.K, oc, &op);
  // per-channel epilogue constants: acc_n = dot(a, w_n)
  //   - wzp_n * rowsum(a)            (separate per-row term when needed)
  //   - xzp * colsum(w_n)  + K4*xzp*wzp_n  + bias_n   (constant, folded here;
  //     K4 because A rows and packed B are both padded consistently: pad
  //     bytes carry a=xzp, w=0, so the identity holds over K4 uniformly)
  op.wzp.assign(op.ocp, 0);
  op.bias_eff.assign(op.ocp, 0);
  op.mult.assign(op.ocp, 0.f);
  bool any_wzp = false;
  for (int nch = 0; nch < oc; ++nch) {
    int64_t colsum = 0;
    for (int k = 0; k < op.K; ++k) colsum += wkn[static_cast<int64_t>(k) * oc + nch];
    const int32_t z = wzp[nch];
    if (z != 0) any_wzp = true;
    op.wzp[nch] = z;
    int64_t c0 = -static_cast<int64_t>(xzp) * colsum +
                 static_cast<int64_t>(op.K4) * xzp * z +
                 (bias ? bias[nch] : 0);
    op.bias_eff[nch] = static_cast<int32_t>(c0);
    op.mult[nch] = mult[nch];
  }
  op.need_rowsum = any_wzp ? 1 : 0;
  const int64_t M = static_cast<int64_t>(oh) * ow;
  if (!op.direct_a)
    p->scratch_a.resize(
        std::max<size_t>(p->scratch_a.size(), static_cast<size_t>(M) * op.K4));
  if (op.need_rowsum)
    p->rowsum.resize(std::max<size_t>(p->rowsum.size(), static_cast<size_t>(M)));
  p->ops.push_back(std::move(op));
  return 0;
}

// depthwise: weights [kh][kw][c] stored s8; depth multiplier 1
__attribute__((visibility("default"))) int nns_q8_add_dw(
    void *h, int in, int out, int n, int hgt, int wid, int c, int oh, int ow,
    int kh, int kw, int sh, int sw, int pt, int pl, const int8_t *w8,
    const int32_t *wzp, const int32_t *bias, const float *mult, int xzp,
    int yzp, int lo, int hi) {
  Prog *p = static_cast<Prog *>(h);
  Op op;
  op.k = OpK::Dw;
  op.in = in;
  op.out = out;
  op.n = n;
  op.h = hgt;
  op.w = wid;
  op.c = c;
  op.oh = oh;
  op.ow = ow;
  op.oc = c;
  op.kh = kh;
  op.kw = kw;
  op.sh = sh;
  op.sw = sw;
  op.pt = pt;
  op.pl = pl;
  // bottom/right pads so every tap index lands inside the padded buffer
  op.pb = std::max(0, (oh - 1) * sh + kh - hgt - pt);
  op.pr = std::max(0, (ow - 1) * sw + kw - wid - pl);
  op.xzp = xzp;
  op.yzp = yzp;
  op.lo = lo;
  op.hi = hi;
  const int c16 = round_up(c, 16), taps = kh * kw;
  op.wf.assign(static_cast<size_t>(taps) * c16, 0.f);
  op.biasf.assign(c16, 0.f);
  op.mult.assign(c16, 0.f);
  // fold: sum_t (a_t - xzp) * (w_t - wzp_c)
  //     = sum_t a_t * wf_tc + (bias_c - xzp * sum_t wf_tc)
  for (int ch = 0; ch < c; ++ch) {
    float wsum = 0.f;
    for (int t = 0; t < taps; ++t) {
      const float wv =
          static_cast<float>(w8[static_cast<int64_t>(t) * c + ch] - wzp[ch]);
      op.wf[static_cast<int64_t>(t) * c16 + ch] = wv;
      wsum += wv;
    }
    op.biasf[ch] = static_cast<float>(bias ? bias[ch] : 0) -
                   static_cast<float>(xzp) * wsum;
    op.mult[ch] = mult[ch];
  }
  const size_t padb = static_cast<size_t>(hgt + op.pt + op.pb) *
                      (wid + op.pl + op.pr) * c;
  p->scratch_pad.resize(std::max(p->scratch_pad.size(), padb));
  p->ops.push_back(std::move(op));
  return 0;
}

__attribute__((visibility("default"))) int nns_q8_add_add(
    void *h, int a, int b, int out, int64_t elems, float ka, float kb,
    float c0, int lo, int hi) {
  Prog *p = static_cast<Prog *>(h);
  Op op;
  op.k = OpK::Add;
  op.in = a;
  op.in2 = b;
  op.out = out;
  op.elems = elems;
  op.ka = ka;
  op.kb = kb;
  op.c0 = c0;
  op.lo = lo;
  op.hi = hi;
  p->ops.push_back(std::move(op));
  return 0;
}

__attribute__((visibility("default"))) int nns_q8_add_avgpool(
    void *h, int in, int out, int n, int hgt, int wid, int c, int oh, int ow,
    int kh, int kw, int sh, int sw, int pt, int pl, int xzp, float ratio,
    int yzp, int lo, int hi) {
  Prog *p = static_cast<Prog *>(h);
  Op op;
  op.k = OpK::AvgPool;
  op.in = in;
  op.out = out;
  op.n = n;
  op.h = hgt;
  op.w = wid;
  op.c = c;
  op.oh = oh;
  op.ow = ow;
  op.oc = c;
  op.kh = kh;
  op.kw = kw;
  op.sh = sh;
  op.sw = sw;
  op.pt = pt;
  op.pl = pl;
  op.xzp = xzp;
  op.ratio = ratio;
  op.yzp = yzp;
  op.lo = lo;
  op.hi = hi;
  p->ops.push_back(std::move(op));
  return 0;
}

__attribute__((visibility("default"))) int nns_q8_add_softmax(
    void *h, int in, int out, int rows, int cols, float s_in, int xzp,
    float inv_s_out, int yzp, float beta) {
  Prog *p = static_cast<Prog *>(h);
  Op op;
  op.k = OpK::Softmax;
  op.in = in;
  op.out = out;
  op.rows = rows;
  op.cols = cols;
  op.s_in = s_in;
  op.xzp = xzp;
  op.inv_s_out = inv_s_out;
  op.yzp = yzp;
  op.beta = beta;
  p->ops.push_back(std::move(op));
  return 0;
}

__attribute__((visibility("default"))) int nns_q8_run(void *h,
                                                      const uint8_t **ins,
                                                      uint8_t **outs) {
  Prog *p = static_cast<Prog *>(h);
  if (p->simd < 0) p->simd = detect_simd();
  for (size_t i = 0; i < p->ins.size(); ++i) {
    Buf &b = p->bufs[p->ins[i]];
    std::memcpy(bptr(p, p->ins[i]), ins[i], static_cast<size_t>(b.nbytes));
  }
  for (const Op &op : p->ops) run_op(p, op);
  for (size_t i = 0; i < p->outs.size(); ++i) {
    Buf &b = p->bufs[p->outs[i]];
    std::memcpy(outs[i], bptr(p, p->outs[i]), static_cast<size_t>(b.nbytes));
  }
  return 0;
}

}  // extern "C"
