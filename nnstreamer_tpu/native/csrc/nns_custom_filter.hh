/* nns_custom_filter.hh — header-only C++ class adapter over the C ABI.
 *
 * Reference analog: tensor_filter_cpp
 * (ext/nnstreamer/tensor_filter/tensor_filter_cpp.cc — user-written C++
 * classes with getInputDim/getOutputDim/setInputDim/invoke registered as
 * filters). Here a class derives from nns::CustomFilter and ONE macro
 * emits the extern "C" vtable of nns_custom_filter.h, so the same .so
 * loads with:
 *     tensor_filter framework=custom model=/path/libmyfilter.so
 *
 * Usage:
 *     #include "nns_custom_filter.hh"
 *     class Doubler : public nns::CustomFilter {
 *      public:
 *       explicit Doubler(const std::string &options) {}
 *       bool get_info(nns_tensors_spec *in, nns_tensors_spec *out) override {
 *         ...fill specs...; return true;
 *       }
 *       int invoke(const nns_tensor_view *in, uint32_t n_in,
 *                  nns_tensor_view *out, uint32_t n_out) override { ... }
 *     };
 *     NNS_REGISTER_CUSTOM_FILTER(Doubler)
 *
 * Static-shape classes override get_info(); dynamic-shape classes
 * override set_input() (reference setInputDimension). The base class
 * implements each in terms of the other where possible, matching the
 * loader's fallback rules (backends/custom_c.py: a failing get_info is
 * tolerated, a PRESENT-but-failing set_input aborts negotiation).
 * Exceptions never cross the C boundary.
 */
#ifndef NNS_CUSTOM_FILTER_HH
#define NNS_CUSTOM_FILTER_HH

#include <string>

#include "nns_custom_filter.h"

namespace nns {

class CustomFilter {
 public:
  virtual ~CustomFilter() = default;

  /* Static-shape filters: declare both specs. */
  virtual bool get_info(nns_tensors_spec * /*in*/, nns_tensors_spec * /*out*/) {
    return false;
  }

  /* Dynamic-shape filters: derive the output spec from the negotiated
   * input. Default: a static filter's declared output works for any
   * accepted input (the loader's own fallback when set_input is absent). */
  virtual bool set_input(const nns_tensors_spec * /*in*/,
                         nns_tensors_spec *out) {
    nns_tensors_spec scratch_in;
    return get_info(&scratch_in, out);
  }

  virtual int invoke(const nns_tensor_view *in, uint32_t n_in,
                     nns_tensor_view *out, uint32_t n_out) = 0;
};

}  // namespace nns

#define NNS_REGISTER_CUSTOM_FILTER(CLASS)                                     \
  extern "C" {                                                                \
  int32_t nns_custom_abi_version(void) { return NNS_CUSTOM_ABI_VERSION; }     \
  void *nns_custom_open(const char *options) {                                \
    try {                                                                     \
      /* upcast BEFORE erasing the type: with multiple inheritance the     */ \
      /* CustomFilter base may not sit at the CLASS address, and the other */ \
      /* entries static_cast the void* back to CustomFilter*               */ \
      nns::CustomFilter *p = new CLASS(std::string(options ? options : "")); \
      return p;                                                               \
    } catch (...) {                                                           \
      return nullptr;                                                         \
    }                                                                         \
  }                                                                           \
  void nns_custom_close(void *h) {                                            \
    try {                                                                     \
      delete static_cast<nns::CustomFilter *>(h);                             \
    } catch (...) {                                                           \
    }                                                                         \
  }                                                                           \
  int nns_custom_invoke(void *h, const nns_tensor_view *in, uint32_t n_in,    \
                        nns_tensor_view *out, uint32_t n_out) {               \
    try {                                                                     \
      return static_cast<nns::CustomFilter *>(h)->invoke(in, n_in, out,       \
                                                         n_out);              \
    } catch (...) {                                                           \
      return -1;                                                              \
    }                                                                         \
  }                                                                           \
  int nns_custom_get_info(void *h, nns_tensors_spec *in_spec,                 \
                          nns_tensors_spec *out_spec) {                       \
    try {                                                                     \
      return static_cast<nns::CustomFilter *>(h)->get_info(in_spec, out_spec) \
                 ? 0                                                          \
                 : -1;                                                        \
    } catch (...) {                                                           \
      return -1;                                                              \
    }                                                                         \
  }                                                                           \
  int nns_custom_set_input(void *h, const nns_tensors_spec *in_spec,          \
                           nns_tensors_spec *out_spec) {                      \
    try {                                                                     \
      return static_cast<nns::CustomFilter *>(h)->set_input(in_spec,          \
                                                            out_spec)        \
                 ? 0                                                          \
                 : -1;                                                        \
    } catch (...) {                                                           \
      return -1;                                                              \
    }                                                                         \
  }                                                                           \
  } /* extern "C" */

#endif /* NNS_CUSTOM_FILTER_HH */
