"""In-band stream events and out-of-band bus messages (L0' substrate).

Reference analog: GStreamer events (EOS, CAPS, SEGMENT, QOS) and bus messages
(ERROR, ELEMENT, STATE_CHANGED) that the reference consumes from its L0, e.g.
QoS throttle events produced by ``tensor_rate``
(gst/nnstreamer/elements/gsttensor_rate.c:452-465) and handled by
``tensor_filter`` (tensor_filter/tensor_filter.c:512).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional


class EventType(enum.Enum):
    CAPS = "caps"          # downstream: negotiated caps follow
    SEGMENT = "segment"    # downstream: new stream segment
    EOS = "eos"            # downstream: end of stream
    FLUSH = "flush"        # both: drop queued data
    QOS = "qos"            # upstream: throttle/lateness feedback
    CUSTOM = "custom"


@dataclass
class Event:
    type: EventType
    data: dict = field(default_factory=dict)

    @classmethod
    def eos(cls) -> "Event":
        return cls(EventType.EOS)

    @classmethod
    def caps(cls, caps) -> "Event":
        return cls(EventType.CAPS, {"caps": caps})

    @classmethod
    def qos_throttle(cls, delay_s: float) -> "Event":
        """Reference: GST_QOS_TYPE_THROTTLE with timediff=delay."""
        return cls(EventType.QOS, {"throttle_delay_s": delay_s})

    def __repr__(self):
        return f"Event<{self.type.value} {self.data}>"


class MessageType(enum.Enum):
    ERROR = "error"
    WARNING = "warning"
    EOS = "eos"
    ELEMENT = "element"          # element-specific payload (trainer progress...)
    LATENCY = "latency"          # an element's latency estimate changed:
    # re-run Pipeline.query_latency() (reference gst_message_new_latency)
    STATE_CHANGED = "state-changed"


@dataclass
class Message:
    type: MessageType
    source: str              # element name
    data: dict = field(default_factory=dict)

    def __repr__(self):
        return f"Message<{self.type.value} from={self.source} {self.data}>"
