"""Stream buffers (L1).

Reference analog: ``GstBuffer`` carrying one ``GstMemory`` chunk per tensor
plus pts/dts/duration and attachable metas (``gst_tensor_buffer_get_nth_memory``
/ ``append_memory``, gst/nnstreamer/nnstreamer_plugin_api_impl.c:1547-1790;
``GstMetaQuery`` client routing, gst/nnstreamer/tensor_meta.c).

TPU-first redesign: a ``Buffer`` holds a list of arrays that may live on host
(numpy, zero-copy views) *or* on device (jax.Array) — elements that chain
device-resident arrays between jitted stages never bounce through host memory,
which is the reference's biggest per-frame cost (its invoke path maps/copies
every tensor on the streaming thread, tensor_filter.c:702-816).
"""
from __future__ import annotations

import sys as _sys
import time
from dataclasses import dataclass, field, replace
from typing import Any, Optional, Sequence

import numpy as np

from .tensors import DataType, TensorFormat, TensorSpec, TensorsInfo

Array = Any  # np.ndarray | jax.Array


def _is_device_array(a) -> bool:
    return hasattr(a, "addressable_shards")  # jax.Array without importing jax here


@dataclass
class Buffer:
    """One frame of a tensor (or media) stream.

    ``tensors`` — the payload chunks. For ``other/tensors`` streams each entry
    is one tensor; for media streams there is a single entry (raw frame bytes
    viewed as an array).
    ``pts`` — presentation timestamp, seconds (float, monotonic clock domain).
    ``meta`` — attachable key/value metas (e.g. ``client_id`` for query
    routing — reference ``GstMetaQuery``).
    """

    tensors: list
    pts: Optional[float] = None
    duration: Optional[float] = None
    offset: Optional[int] = None  # frame sequence number
    meta: dict = field(default_factory=dict)

    # ------------------------------------------------------------------
    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    @property
    def nbytes(self) -> int:
        return sum(int(np.asarray(t).nbytes) if not _is_device_array(t) else t.nbytes
                   for t in self.tensors)

    @property
    def on_device(self) -> bool:
        return any(_is_device_array(t) for t in self.tensors)

    def spec(self) -> TensorsInfo:
        """Per-frame specs (the FLEXIBLE format's per-memory header analog)."""
        return TensorsInfo.from_arrays(
            [t for t in self.tensors], TensorFormat.FLEXIBLE
        )

    # ------------------------------------------------------------------
    def as_numpy(self) -> "Buffer":
        """Materialize device arrays on host. No copy for host arrays.

        This is THE accounted device→host path: the pull is an explicit
        ``jax.device_get`` (legal under the NNS_XFERCHECK disallow
        scopes, where an implicit ``np.asarray`` on a device array would
        trip the transfer guard) and its bytes land in the transfer
        ledger when the sanitizer is armed."""
        if not self.on_device:
            return self
        import jax  # deliberately lazy: core/ never imports jax at module scope

        host = [jax.device_get(t) if _is_device_array(t) else np.asarray(t)
                for t in self.tensors]
        # sys.modules lookup, not an import: core/ must not import the
        # analysis package (graph lint imports core.caps — cycle risk)
        _san = _sys.modules.get("nnstreamer_tpu.analysis.sanitizer")
        if _san is not None and _san.XFER:
            _san.note_transfer(
                "buffer:as_numpy", "d2h",
                sum(int(h.nbytes) for h, t in zip(host, self.tensors)
                    if _is_device_array(t)))
        return replace(self, tensors=host)

    def with_tensors(self, tensors: Sequence[Array]) -> "Buffer":
        return replace(self, tensors=list(tensors))

    def with_meta(self, **kv) -> "Buffer":
        return replace(self, meta={**self.meta, **kv})

    def copy_metadata_from(self, other: "Buffer") -> "Buffer":
        self.pts = other.pts
        self.duration = other.duration
        self.offset = other.offset
        self.meta = dict(other.meta)
        return self

    @classmethod
    def of(cls, *tensors: Array, pts: Optional[float] = None, **kw) -> "Buffer":
        return cls(list(tensors), pts=pts, **kw)

    def __repr__(self):
        shapes = ",".join(
            f"{np.asarray(t).dtype if not _is_device_array(t) else t.dtype}"
            f"{tuple(t.shape)}"
            for t in self.tensors
        )
        loc = "dev" if self.on_device else "host"
        return f"Buffer<{shapes} {loc} pts={self.pts}>"


def clock_now() -> float:
    """Pipeline clock: monotonic seconds (GStreamer clock analog)."""
    return time.monotonic()
