"""Tensor frame wire format (L1/L5 shared).

One binary framing used everywhere the reference uses flatbuf/protobuf/
flexbuf serialization (ext/nnstreamer/tensor_decoder/tensordec-{flatbuf,
flexbuf,protobuf}.*, the mqtt 1024-byte header gst/mqtt/mqttcommon.h:49-61,
and the nns-edge data list) — header + per-tensor {flags, dtype, shape,
payload}:

  magic  "NNST"  | u16 version | u32 n_tensors | f64 pts (nan=None) |
  u32 meta_len | meta JSON | per tensor:
    v1:  u8 dtype_len | dtype name | u8 rank | u64*rank dims | u64 nbytes | raw
    v2:  u8 flags | <v1 tensor header> | payload

``flags`` bit0 = sparse: dtype/dims describe the DENSE tensor and the
payload is ``u32 nnz | int32 idx[nnz] | value[nnz]`` — the COO form of the
reference's per-memory ``GstTensorMetaInfo.sparse_info`` header
(gst/nnstreamer/elements/gsttensor_sparseutil.c:116,
include/tensor_typedef.h:280), so a sparse stream survives every process
boundary (query/edge/mqtt/grpc) exactly like the reference's does. Dense
frames are EMITTED as v1 so not-yet-upgraded peers keep reading them
during a rolling upgrade; both versions are accepted on read.

Buffer ``meta`` rides as JSON: numpy scalars/arrays are coerced, anything
else non-serializable raises (a silent drop turned sparse frames into
garbage downstream once — VERDICT r02 weak #3).
"""
from __future__ import annotations

import json
import math
import struct
import sys as _sys
from typing import List, Optional

import numpy as np

from .buffer import Buffer
from .tensors import DataType, TensorSpec

MAGIC = b"NNST"
VERSION = 2
_FLAG_SPARSE = 0x01

# declared hostile-peer limits (docs/transport.md "hostile peer"
# contract): every wire-derived size is checked against these BEFORE it
# drives an allocation or a loop, and the violation raises the decoder's
# typed error (ValueError here; transport/frame.py imports these and
# raises FrameError, a ValueError subclass). A 4-byte count field from a
# corrupt or hostile peer must never become a multi-GB allocation.
MAX_TENSORS = 256
MAX_META_BYTES = 1 << 20        # 1 MiB of JSON/tagged-binary meta
MAX_PAYLOAD_BYTES = 1 << 33     # 8 GiB total tensor payload per frame

# both sides of the v2/sparse header fields share these layouts — one
# source of truth, so encoder and decoder cannot drift independently
_FLAGS_DTLEN = struct.Struct("<BB")   # u8 flags | u8 dtype-name length
_NBYTES_NNZ = struct.Struct("<QI")    # u64 nbytes | u32 nnz (sparse)

# meta key consumed into per-tensor sparse headers rather than the JSON blob
SPARSE_META_KEY = "sparse_specs"


# ndarrays in meta coerce to JSON lists only up to this many elements;
# anything larger (e.g. the image-segment decoder's full H×W class_map,
# an in-process convenience) would inflate every frame with megabytes of
# JSON text — such keys are dropped from the wire with a warning (ship
# large arrays as tensors); all OTHER non-serializable meta raises
_META_ARRAY_MAX = 256
_warned_meta_keys = set()


def _meta_default(o):
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        if o.size > _META_ARRAY_MAX:
            # nested inside a list/dict value the top-level drop can't see:
            # refuse loudly rather than inflate the frame
            raise TypeError(
                f"ndarray of {o.size} elements nested in meta "
                f"(>{_META_ARRAY_MAX}); ship large arrays as tensors")
        return o.tolist()
    if isinstance(o, (set, frozenset)):
        return sorted(o)
    raise TypeError(f"{type(o).__name__} is not wire-serializable")


def _encode_meta(meta: dict) -> bytes:
    """JSON-encode buffer meta, coercing numpy values; raise naming the
    offending keys instead of silently dropping them. Oversized ndarray
    values are dropped loudly (warning, once per key)."""
    from ..utils.log import logger

    items = {}
    # sorted: the emitted bytes must not depend on dict insertion order
    # (canonical encoding — two peers packing the same meta produce the
    # same frame, and wirefuzz byte-parity checks rely on it)
    for k, v in sorted(meta.items(), key=lambda kv: str(kv[0])):
        if k == SPARSE_META_KEY:
            continue  # carried in the per-tensor headers
        if isinstance(v, np.ndarray) and v.size > _META_ARRAY_MAX:
            if k not in _warned_meta_keys:
                _warned_meta_keys.add(k)
                logger.warning(
                    "meta['%s'] (%d-element ndarray) dropped from the wire: "
                    "arrays >%d elements must travel as tensors, not meta",
                    k, v.size, _META_ARRAY_MAX)
            continue
        items[str(k)] = v
    try:
        return json.dumps(items, default=_meta_default,
                          sort_keys=True).encode()
    except (TypeError, ValueError):
        bad = []
        for k, v in sorted(items.items()):
            try:
                json.dumps(v, default=_meta_default)
            except (TypeError, ValueError):
                bad.append(k)
        raise TypeError(
            f"buffer meta key(s) {bad} are not wire-serializable; "
            "convert to JSON-able values before crossing a process boundary")


def pack_tensors(buf: Buffer, extra_meta: Optional[dict] = None) -> memoryview:
    """Serialize one frame into a single freshly-gathered buffer.

    Headers are built in Python (tiny); tensor payloads are copied exactly
    once, by one native memcpy-gather pass — the reference's encoders pay a
    per-tensor copy plus a join copy. Returns a ``memoryview`` (socket send
    paths consume it without another copy; call ``bytes()`` if an owning
    immutable copy is needed).

    Sparse frames (``buf.meta['sparse_specs']`` from tensor_sparse_enc,
    tensors laid out as ``idx0, val0, idx1, val1, ...``) are written with
    the sparse flag: one wire tensor per DENSE tensor, dense spec in the
    header, COO payload.
    """
    from .. import native

    arrays = [np.ascontiguousarray(np.asarray(t)) for t in buf.as_numpy().tensors]
    meta = dict(buf.meta)
    if extra_meta:
        meta.update(extra_meta)
    specs = meta.get(SPARSE_META_KEY)
    meta_blob = _encode_meta(meta)
    n_wire = len(arrays) if specs is None else len(specs)
    if specs is not None and len(arrays) != 2 * len(specs):
        raise ValueError(
            f"sparse frame carries {len(arrays)} arrays for {len(specs)} specs "
            "(want idx/value pairs)")
    # dense frames go out as v1 (no flags byte) so not-yet-upgraded peers
    # keep reading them during a rolling upgrade; only sparse needs v2
    version = 1 if specs is None else VERSION
    parts: List[np.ndarray] = [_bview(
        MAGIC
        + struct.pack("<HIdI", version, n_wire,
                      math.nan if buf.pts is None else buf.pts, len(meta_blob))
        + meta_blob
    )]
    if specs is None:
        for a in arrays:
            dt = DataType.from_any(a.dtype).value.encode()
            parts.append(_bview(
                struct.pack("<B", len(dt)) + dt + struct.pack("<B", a.ndim)
                + struct.pack(f"<{a.ndim}Q", *a.shape)
                + struct.pack("<Q", a.nbytes)))
            parts.append(a.reshape(-1).view(np.uint8))
    else:
        for i, spec in enumerate(specs):
            idx = np.ascontiguousarray(arrays[2 * i], np.int32)
            vals = arrays[2 * i + 1]
            dtype = DataType.from_any(spec.dtype)
            if DataType.from_any(vals.dtype) is not dtype:
                raise ValueError(
                    f"sparse tensor {i}: values dtype {vals.dtype} != "
                    f"dense spec dtype {dtype.value}")
            if idx.size != vals.size:
                raise ValueError(
                    f"sparse tensor {i}: {idx.size} indices but "
                    f"{vals.size} values")
            shape = tuple(int(d) for d in spec.shape)
            nbytes = 4 + idx.nbytes + vals.nbytes
            dt = dtype.value.encode()
            parts.append(_bview(
                _FLAGS_DTLEN.pack(_FLAG_SPARSE, len(dt)) + dt
                + struct.pack("<B", len(shape))
                + struct.pack(f"<{len(shape)}Q", *shape)
                + _NBYTES_NNZ.pack(nbytes, idx.size)))
            parts.append(idx.view(np.uint8))
            parts.append(vals.reshape(-1).view(np.uint8))
    frame = native.gather(parts).data
    _note_wire_bytes("wire:encode", frame.nbytes)
    return frame


def _note_wire_bytes(stage: str, nbytes: int) -> None:
    """NNS_XFERCHECK byte accounting for the codec choke point. A
    sys.modules lookup, not an import: core/ must not import the
    analysis package (graph lint imports core.caps — cycle risk); one
    dict-get + attribute check when the sanitizer is off."""
    _san = _sys.modules.get("nnstreamer_tpu.analysis.sanitizer")
    if _san is not None and _san.XFER:
        _san.note_transfer(stage, "host", nbytes)


def _bview(b: bytes) -> np.ndarray:
    return np.frombuffer(b, np.uint8)


def unpack_tensors(blob) -> Buffer:
    """Deserialize one frame from any contiguous byte buffer (bytes,
    bytearray, memoryview, or uint8 ndarray). Accepts wire v1 (no flags
    byte) and v2. A sparse frame reconstructs the tensor_sparse_enc layout:
    idx/value array pairs + ``meta['sparse_specs']``."""
    blob = memoryview(blob).cast("B")
    if bytes(blob[:4]) != MAGIC:
        raise ValueError("bad tensor frame magic")
    off = 4
    try:
        version, n, pts, meta_len = struct.unpack_from("<HIdI", blob, off)
        if version not in (1, VERSION):
            raise ValueError(f"unsupported frame version {version}")
        off += struct.calcsize("<HIdI")
        # hostile-peer bounds: every wire-derived size is validated
        # against the declared limit (and against what actually arrived)
        # BEFORE it drives an allocation or a loop
        if n > MAX_TENSORS:
            raise ValueError(
                f"frame declares {n} tensors (limit {MAX_TENSORS})")
        if meta_len > MAX_META_BYTES or off + meta_len > len(blob):
            raise ValueError(
                f"torn/oversized meta: {meta_len} bytes declared, "
                f"{len(blob) - off} available (limit {MAX_META_BYTES})")
        meta = json.loads(bytes(blob[off:off + meta_len]) or b"{}")
        off += meta_len
        tensors: List[np.ndarray] = []
        specs: List[TensorSpec] = []
        for ti in range(n):
            if version >= 2:
                flags, dt_len = _FLAGS_DTLEN.unpack_from(blob, off)
                off += _FLAGS_DTLEN.size
            else:
                flags = 0
                (dt_len,) = struct.unpack_from("<B", blob, off)
                off += 1
            dtype = DataType(bytes(blob[off:off + dt_len]).decode())
            off += dt_len
            (rank,) = struct.unpack_from("<B", blob, off)
            off += 1
            shape = struct.unpack_from(f"<{rank}Q", blob, off)
            off += 8 * rank
            if flags & _FLAG_SPARSE:
                # a frame is all-sparse or all-dense (tensor_sparse_enc
                # layout pairs idx/values positionally — mixing would
                # misalign them)
                if len(tensors) != 2 * len(specs):
                    raise ValueError(
                        f"tensor {ti}: sparse/dense mix in one frame")
                nbytes, nnz = _NBYTES_NNZ.unpack_from(blob, off)
                off += 8  # nnz is part of the nbytes-counted payload
                itemsize = np.dtype(dtype.np_dtype).itemsize
                if (nbytes > MAX_PAYLOAD_BYTES
                        or 4 + nnz * (4 + itemsize) > nbytes
                        or off + nbytes > len(blob)):
                    raise ValueError(
                        f"tensor {ti}: torn/oversized sparse payload "
                        f"({nnz} nnz, {nbytes} bytes declared, "
                        f"{len(blob) - off} available)")
                idx = np.frombuffer(blob, np.int32, count=nnz,
                                    offset=off + 4)
                vals = np.frombuffer(blob, dtype.np_dtype, count=nnz,
                                     offset=off + 4 + idx.nbytes)
                tensors.extend([idx.copy(), vals.copy()])
                specs.append(TensorSpec(shape, dtype))
            else:
                if specs:
                    raise ValueError(
                        f"tensor {ti}: sparse/dense mix in one frame")
                (nbytes,) = struct.unpack_from("<Q", blob, off)
                off += 8
                count = 1
                for d in shape:
                    count *= int(d)  # Python ints: no silent overflow
                itemsize = np.dtype(dtype.np_dtype).itemsize
                if (nbytes > MAX_PAYLOAD_BYTES
                        or count * itemsize != nbytes
                        or off + nbytes > len(blob)):
                    raise ValueError(
                        f"tensor {ti}: payload mismatch (shape {shape} "
                        f"wants {count * itemsize} bytes, {nbytes} "
                        f"declared, {len(blob) - off} available)")
                a = np.frombuffer(blob, dtype.np_dtype, count=count,
                                  offset=off)
                tensors.append(a.reshape(shape or ()).copy())
            off += nbytes
    except (struct.error, UnicodeDecodeError) as e:
        # a truncated/corrupt frame must surface as the decoder's TYPED
        # error, never a bare struct.error killing a reader thread
        raise ValueError(f"torn tensor frame: {e}") from e
    out = Buffer(tensors, pts=None if math.isnan(pts) else pts)
    out.meta.update(meta)
    if specs:
        out.meta[SPARSE_META_KEY] = specs
    _note_wire_bytes("wire:decode", off)
    return out
