"""Stream capabilities ("caps") and negotiation algebra (L1).

The reference gets caps negotiation from GStreamer (``GstCaps``/``GstStructure``,
intersect/fixate, used throughout e.g. ``gst/nnstreamer/nnstreamer_plugin_api_impl.c``
``gst_tensors_config_from_caps``). We supply that layer ourselves: a ``Caps`` is
an ordered list of ``Structure`` alternatives; a ``Structure`` is a media-type
plus constrained fields. Field constraints are concrete values, ``ValueList``
(choice sets), ``IntRange``, or ``ANY``.

Media types (reference caps names, tensor_typedef.h:46-79):
  * ``other/tensors``        — tensor streams (format static/flexible/sparse)
  * ``video/raw``            — raw video (reference ``video/x-raw``)
  * ``audio/raw``            — raw audio  (reference ``audio/x-raw``)
  * ``text/plain``, ``application/octet-stream`` — text / opaque bytes
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

from .tensors import TensorFormat, TensorsInfo

TENSORS_MIME = "other/tensors"
VIDEO_MIME = "video/raw"
AUDIO_MIME = "audio/raw"
TEXT_MIME = "text/plain"
OCTET_MIME = "application/octet-stream"


class _Any:
    """Wildcard field value."""

    _inst: "_Any" = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "ANY"


ANY = _Any()


@dataclass(frozen=True)
class IntRange:
    lo: int
    hi: int  # inclusive

    def __contains__(self, v) -> bool:
        return isinstance(v, int) and self.lo <= v <= self.hi

    def intersect(self, other):
        if isinstance(other, IntRange):
            lo, hi = max(self.lo, other.lo), min(self.hi, other.hi)
            if lo > hi:
                return None
            return lo if lo == hi else IntRange(lo, hi)
        if isinstance(other, ValueList):
            return other.intersect(self)  # keep intersection symmetric
        if other in self:
            return other
        return None

    def fixate(self):
        return self.lo

    def __repr__(self):
        return f"[{self.lo},{self.hi}]"


@dataclass(frozen=True)
class ValueList:
    values: tuple

    def __post_init__(self):
        object.__setattr__(self, "values", tuple(self.values))

    def __contains__(self, v) -> bool:
        return v in self.values

    def intersect(self, other):
        if isinstance(other, ValueList):
            common = tuple(v for v in self.values if v in other.values)
            if not common:
                return None
            return common[0] if len(common) == 1 else ValueList(common)
        if isinstance(other, IntRange):
            common = tuple(v for v in self.values if v in other)
            if not common:
                return None
            return common[0] if len(common) == 1 else ValueList(common)
        if other in self.values:
            return other
        return None

    def fixate(self):
        return self.values[0]

    def __repr__(self):
        return "{" + ",".join(str(v) for v in self.values) + "}"


def _intersect_value(a, b):
    """Intersect two field constraints; None means empty intersection."""
    if a is ANY:
        return b
    if b is ANY:
        return a
    if isinstance(a, (IntRange, ValueList)):
        return a.intersect(b)
    if isinstance(b, (IntRange, ValueList)):
        return b.intersect(a)
    if a == b:
        return a
    # Launch-string fields are weakly typed: "dimensions=2" parses as int 2
    # while an element emits the dim *string* "2". Compare string forms before
    # declaring a mismatch.
    if type(a) is not type(b) and str(a) == str(b):
        return a
    return None


def _is_fixed_value(v) -> bool:
    return not isinstance(v, (IntRange, ValueList, _Any))


@dataclass(frozen=True)
class Structure:
    """One caps alternative: media type + fields."""

    media_type: str
    fields: tuple = ()  # tuple of (key, value) pairs, insertion-ordered

    @classmethod
    def new(cls, media_type: str, **fields) -> "Structure":
        return cls(media_type, tuple(fields.items()))

    def as_dict(self) -> dict:
        return dict(self.fields)

    def get(self, key, default=None):
        for k, v in self.fields:
            if k == key:
                return v
        return default

    def with_fields(self, **updates) -> "Structure":
        d = self.as_dict()
        d.update(updates)
        return Structure(self.media_type, tuple(d.items()))

    def intersect(self, other: "Structure") -> Optional["Structure"]:
        if self.media_type != other.media_type:
            return None
        out = {}
        d1, d2 = self.as_dict(), other.as_dict()
        for k in {**d1, **d2}:
            a, b = d1.get(k, ANY), d2.get(k, ANY)
            v = _intersect_value(a, b)
            if v is None:
                return None
            if v is not ANY:
                out[k] = v
        return Structure(self.media_type, tuple(out.items()))

    @property
    def is_fixed(self) -> bool:
        return all(_is_fixed_value(v) for _, v in self.fields)

    def fixate(self) -> "Structure":
        out = []
        for k, v in self.fields:
            if isinstance(v, (IntRange, ValueList)):
                v = v.fixate()
            elif v is ANY:
                continue
            out.append((k, v))
        return Structure(self.media_type, tuple(out))

    def __str__(self):
        parts = [self.media_type]
        for k, v in self.fields:
            parts.append(f"{k}={v}")
        return ",".join(parts)


@dataclass(frozen=True)
class Caps:
    """Ordered list of ``Structure`` alternatives (GstCaps analog)."""

    structures: tuple = ()

    def __post_init__(self):
        object.__setattr__(self, "structures", tuple(self.structures))

    @classmethod
    def new(cls, media_type: str, **fields) -> "Caps":
        return cls((Structure.new(media_type, **fields),))

    @classmethod
    def any_of(cls, *structures: Structure) -> "Caps":
        return cls(tuple(structures))

    @property
    def is_empty(self) -> bool:
        return not self.structures

    @property
    def is_fixed(self) -> bool:
        return len(self.structures) == 1 and self.structures[0].is_fixed

    def intersect(self, other: "Caps") -> "Caps":
        out = []
        for a in self.structures:
            for b in other.structures:
                s = a.intersect(b)
                if s is not None and s not in out:
                    out.append(s)
        return Caps(tuple(out))

    def can_intersect(self, other: "Caps") -> bool:
        return not self.intersect(other).is_empty

    def fixate(self) -> "Caps":
        if self.is_empty:
            raise ValueError("cannot fixate empty caps")
        return Caps((self.structures[0].fixate(),))

    @property
    def first(self) -> Structure:
        if self.is_empty:
            raise ValueError("empty caps")
        return self.structures[0]

    def __str__(self):
        if self.is_empty:
            return "EMPTY"
        return ";".join(str(s) for s in self.structures)


# ---------------------------------------------------------------------------
# tensors <-> caps bridging (reference gst_tensor_caps_from_config /
# gst_tensors_config_from_caps, nnstreamer_plugin_api_impl.c)
# ---------------------------------------------------------------------------

def caps_from_tensors_info(info: TensorsInfo, framerate=None) -> Caps:
    fields = info.to_fields()
    if framerate is not None:
        fields["framerate"] = framerate
    return Caps.new(TENSORS_MIME, **fields)


def tensors_info_from_caps(caps: Caps) -> TensorsInfo:
    s = caps.first
    if s.media_type != TENSORS_MIME:
        raise ValueError(f"not a tensor caps: {s.media_type}")
    return TensorsInfo.from_fields(s.as_dict())


def caps_tensor_format(caps: Caps):
    """The TensorFormat a tensor caps declares, or None for non-tensor /
    format-unconstrained caps (used by negotiation-adjacent consumers
    like the static linter's flexible-stream checks)."""
    if caps.is_empty:
        return None
    s = caps.first
    if s.media_type != TENSORS_MIME:
        return None
    fmt = s.get("format")
    if fmt is None or not isinstance(fmt, str):
        return None
    try:
        return TensorFormat(fmt)
    except ValueError:
        return None


def tensors_any_caps() -> Caps:
    """Template caps accepting any tensor stream."""
    return Caps.any_of(
        Structure.new(TENSORS_MIME, format=ValueList(tuple(f.value for f in TensorFormat)))
    )


# IDL byte-stream MIMEs (reference: other/protobuf-tensor caps of
# ext/nnstreamer/extra/nnstreamer_protobuf.h, flatbuf analog; other/flexbuf
# is the tensordec-flexbuf.cc output MIME the corpus pipes through
# capsfilters: ``tensor_decoder mode=flexbuf ! other/flexbuf ! ...``)
PROTOBUF_MIME = "other/protobuf-tensor"
FLATBUF_MIME = "other/flatbuf-tensor"
FLEXBUF_MIME = "other/flexbuf"

ALL_MIMES = (TENSORS_MIME, VIDEO_MIME, AUDIO_MIME, TEXT_MIME, OCTET_MIME,
             PROTOBUF_MIME, FLATBUF_MIME, FLEXBUF_MIME,
             # compressed-image streams (filesrc ! image/png,... ! pngdec —
             # the reference test idiom; imagedec sniffs the actual codec)
             "image/png", "image/jpeg", "image/bmp",
             "image/x-portable-graymap", "image/x-portable-pixmap",
             "image/x-portable-anymap")


def any_media_caps() -> Caps:
    """Template caps accepting every media type (queue/tee/sink templates)."""
    return Caps(tuple(Structure.new(m) for m in ALL_MIMES))


# ---------------------------------------------------------------------------
# caps-string parsing for launch lines: "other/tensors,format=static,
# dimensions=3:224:224:1,types=uint8" — the reference's capsfilter syntax.
# ---------------------------------------------------------------------------

_NUM_RE = re.compile(r"^-?\d+$")
_FLOAT_RE = re.compile(r"^-?\d*\.\d+$")
_RANGE_RE = re.compile(r"^\[\s*(-?\d+)\s*,\s*(-?\d+)\s*\]$")
_LIST_RE = re.compile(r"^\{(.*)\}$")


def _parse_field_value(text: str):
    text = text.strip()
    # GStreamer typed values: `(string)RGB`, `(int)640`, `(fraction)30/1`
    # — strip the annotation, the value parser below infers the type
    if text.startswith("(") and ")" in text:
        text = text[text.index(")") + 1:].strip()
    m = _RANGE_RE.match(text)
    if m:
        return IntRange(int(m.group(1)), int(m.group(2)))
    m = _LIST_RE.match(text)
    if m:
        return ValueList(tuple(_parse_field_value(p) for p in m.group(1).split(",")))
    if _NUM_RE.match(text):
        return int(text)
    if _FLOAT_RE.match(text):
        return float(text)
    if "/" in text and all(_NUM_RE.match(p) for p in text.split("/", 1)):
        num, den = text.split("/", 1)
        return (int(num), int(den))  # framerate fraction
    return text


# GStreamer MIME spellings → our canonical media types, so the
# reference's launch lines (`video/x-raw`, `audio/x-raw`,
# `application/octet-stream`, `text/x-raw`, `other/tensor` singular)
# parse unchanged (reference caps strings appear throughout its
# tests/*/runTest.sh)
_MEDIA_ALIASES = {
    "video/x-raw": VIDEO_MIME,
    "audio/x-raw": AUDIO_MIME,
    "text/x-raw": TEXT_MIME,
    "application/octet-stream": OCTET_MIME,
    "other/tensor": TENSORS_MIME,
}

# field spellings that differ between GStreamer caps and ours
_FIELD_ALIASES = {"dimension": "dimensions", "type": "types"}


def parse_caps_string(text: str) -> Caps:
    structures = []
    for struct_text in text.split(";"):
        parts = _split_fields(struct_text.strip())
        media = _MEDIA_ALIASES.get(parts[0], parts[0])
        fields = {}
        for p in parts[1:]:
            if not p:
                continue
            k, _, v = p.partition("=")
            k = k.strip()
            fields[_FIELD_ALIASES.get(k, k)] = _parse_field_value(v)
        structures.append(Structure.new(media, **fields))
    return Caps(tuple(structures))


def _split_fields(text: str):
    """Split on commas not inside {} or [] (list/range values contain commas)."""
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch in "{[":
            depth += 1
        elif ch in "}]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur).strip())
    return parts


def looks_like_caps(text: str) -> bool:
    head = text.split(",", 1)[0].strip()
    return "/" in head and "=" not in head
